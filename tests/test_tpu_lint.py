"""tpu_lint static-analysis framework: positive/negative fixture pairs per
AST rule, jaxpr-level audits against toy jits, suppression machinery, and the
repo-clean assertion (ref: the reference repo's `tools/` CI-check layer —
op-registry audits / API guards; ours prove the serving engine's
dispatch/sync discipline instead)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis import run_ast_checks
from paddle_tpu.analysis.jaxpr_checks import audit_jaxpr, run_jaxpr_checks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_snippet(tmp_path, code, rule=None, registry=None):
    """Write `code` to a fixture file, lint it, return findings (all, or only
    the given rule's)."""
    p = tmp_path / "fixture.py"
    p.write_text(textwrap.dedent(code))
    fs = run_ast_checks([str(p)], registry=registry)
    if rule is not None:
        fs = [f for f in fs if f.rule == rule]
    return fs


class _RegistryStub:
    """Registry where every site is declared (TPL002 negative fixture)."""
    class _Entry:
        qualname = ""

    def lookup(self, path, qualname):
        return self._Entry()

    def for_path(self, path):
        return []


# ---------------------------------------------------------------------------
# TPL001 — host sync in step()-reachable code
# ---------------------------------------------------------------------------

def test_tpl001_flags_scalarize_in_hot_loop(tmp_path):
    fs = lint_snippet(tmp_path, """
        class Engine:
            def step(self):
                logits = self._decode_fn(1)
                return int(logits)              # scalar sync on device value
    """, rule="TPL001")
    assert len(fs) == 1 and "int" in fs[0].message


def test_tpl001_implicit_bool_flagged(tmp_path):
    fs = lint_snippet(tmp_path, """
        class Engine:
            def step(self):
                flag = self._decode_fn(1)
                if flag:                        # hidden blocking bool()
                    return 1
    """, rule="TPL001")
    assert len(fs) == 1 and "bool" in fs[0].message


def test_tpl001_silent_on_laundered_fetch(tmp_path):
    # int() over an np.asarray result is host work, not a second sync
    fs = lint_snippet(tmp_path, """
        import numpy as np

        class Engine:
            def step(self):
                logits = self._decode_fn(1)
                with self._span("engine.sample.sync"):
                    logits = np.asarray(logits)
                return int(logits[0])
    """, rule="TPL001")
    assert fs == []


def test_tpl001_silent_outside_hot_path(tmp_path):
    fs = lint_snippet(tmp_path, """
        class Engine:
            def debug_dump(self):               # not step()-reachable
                return int(self._decode_fn(1))
    """, rule="TPL001")
    assert fs == []


# ---------------------------------------------------------------------------
# TPL002 — unregistered jit/shard_map site + stale registry entries
# ---------------------------------------------------------------------------

def test_tpl002_flags_unregistered_jit_site(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax

        def build():
            return jax.jit(lambda x: x + 1)
    """, rule="TPL002")
    assert len(fs) == 1 and "not declared" in fs[0].message


def test_tpl002_silent_when_registered(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax

        def build():
            return jax.jit(lambda x: x + 1)
    """, rule="TPL002", registry=_RegistryStub())
    assert fs == []


def test_tpl002_flags_decorator_jit_sites(tmp_path):
    """@jax.jit / @functools.partial(jax.jit, ...) mint programs exactly like
    call-style sites — both registration (TPL002) and donation (TPL003) must
    see them."""
    code = """
        import functools
        import jax

        @jax.jit
        def step_a(pool, x):
            return pool, x

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step_b(pool, x):
            return pool, x
    """
    t2 = lint_snippet(tmp_path, code, rule="TPL002")
    assert len(t2) == 2                  # both decorators are program sources
    t3 = lint_snippet(tmp_path, code, rule="TPL003", registry=_RegistryStub())
    assert len(t3) == 1 and "step_a" in t3[0].message   # only the undonated


def test_tpl002_flags_orphaned_registry_entry(tmp_path):
    """A registry entry whose FILE was deleted/renamed must be flagged even
    though no per-file pass ever visits it."""
    class _Entry:
        path = str(tmp_path / "deleted_module.py")
        qualname = "gone"

    class _Reg:
        PROGRAM_SOURCES = (_Entry(),)

        def lookup(self, path, qualname):
            return None

        def for_path(self, path):
            return []

    (tmp_path / "present.py").write_text("def f():\n    return 1\n")
    fs = run_ast_checks([str(tmp_path)], registry=_Reg())
    assert any(f.rule == "TPL002" and "no longer exists" in f.message
               for f in fs)
    # root spelled through a '.' segment covers the same entries (absolute
    # containment, not relpath string prefixes)
    fs = run_ast_checks([os.path.join(str(tmp_path), ".")], registry=_Reg())
    assert any(f.rule == "TPL002" and "no longer exists" in f.message
               for f in fs)


def test_tpl002_repo_registry_has_no_stale_entries():
    # every declared source must still have a jit site behind it
    fs = [f for f in run_ast_checks([os.path.join(REPO, "paddle_tpu")])
          if f.rule == "TPL002"]
    assert [f for f in fs if not f.suppressed] == [], \
        [f.format() for f in fs]


# ---------------------------------------------------------------------------
# TPL003 — missing donation on large persistent buffers
# ---------------------------------------------------------------------------

def test_tpl003_flags_undonated_pool(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax

        def decode(params, pool, tokens):
            return pool, tokens

        fn = jax.jit(decode)
    """, rule="TPL003", registry=_RegistryStub())
    assert len(fs) == 1 and "donate_argnums" in fs[0].message


def test_tpl003_silent_with_donation(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax

        def decode(params, pool, tokens):
            return pool, tokens

        fn = jax.jit(decode, donate_argnums=(1,))
    """, rule="TPL003", registry=_RegistryStub())
    assert fs == []


# ---------------------------------------------------------------------------
# TPL004 — Python branch on a traced value
# ---------------------------------------------------------------------------

def test_tpl004_flags_value_branch(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax

        def body(x):
            if x > 0:                   # traced: compiles one program per value
                return x
            return -x

        fn = jax.jit(body)
    """, rule="TPL004", registry=_RegistryStub())
    assert len(fs) == 1 and "`x`" in fs[0].message


def test_tpl004_silent_on_static_tests(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax

        def body(x, y):
            if x.shape[0] > 2:          # shapes are static under tracing
                x = x[:2]
            if y is None:
                return x
            if len(x) > 4:
                return x + y
            return x - y

        fn = jax.jit(body)
    """, rule="TPL004", registry=_RegistryStub())
    assert fs == []


# ---------------------------------------------------------------------------
# TPL005 — blocking fetch outside a RecordEvent span
# ---------------------------------------------------------------------------

def test_tpl005_flags_unspanned_fetch(tmp_path):
    fs = lint_snippet(tmp_path, """
        import numpy as np

        class Engine:
            def step(self):
                out = self._decode_fn(1)
                return np.asarray(out)          # untimed blocking fetch
    """, rule="TPL005")
    assert len(fs) == 1 and "RecordEvent" in fs[0].message


def test_tpl005_silent_inside_span(tmp_path):
    fs = lint_snippet(tmp_path, """
        import numpy as np

        class Engine:
            def step(self):
                out = self._decode_fn(1)
                with self._span("engine.sample.sync"):
                    return np.asarray(out)
    """, rule="TPL005")
    assert fs == []


# ---------------------------------------------------------------------------
# TPL006 — broad except around device code
# ---------------------------------------------------------------------------

def test_tpl006_flags_broad_except(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax

        def probe():
            try:
                return jax.devices()
            except Exception:
                return []
    """, rule="TPL006")
    assert len(fs) == 1 and "narrow" in fs[0].message


def test_tpl006_silent_on_narrow_except(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax

        def probe():
            try:
                return jax.devices()
            except RuntimeError:
                return []
    """, rule="TPL006")
    assert fs == []


# ---------------------------------------------------------------------------
# TPL007 — page-state mutation with a double-buffered dispatch in flight
# ---------------------------------------------------------------------------

def test_tpl007_flags_mutation_before_harvest(tmp_path):
    fs = lint_snippet(tmp_path, """
        class Engine:
            def _dispatch(self):
                self._inflight = {"out": 1}     # double-buffer publication

            def _harvest(self, finished):
                self._inflight = None

            def abort(self, rid):
                self.cache.release(rid)         # in-flight batch not harvested
                return True
    """, rule="TPL007")
    assert len(fs) == 1 and "harvest" in fs[0].message \
        and "Engine.abort" in fs[0].message


def test_tpl007_flags_preempt_before_harvest(tmp_path):
    # the oversubscription PR's hazard shape: a public preempt entry point
    # that releases a victim's pages and hands them to a new owner while the
    # double-buffered batch is still in flight — the in-flight harvest would
    # then apply step-n results to step-n+1 page ownership.  (The real
    # engine's preemption runs inside step(), strictly after the step-top
    # harvest, so it passes by construction.)
    fs = lint_snippet(tmp_path, """
        class Engine:
            def _dispatch(self):
                self._inflight = {"out": 1}

            def _harvest(self, finished):
                self._inflight = None

            def preempt_request(self, slot):
                self.cache.release(slot)        # victim pages freed...
                self.cache.allocate(slot, 8)    # ...and reassigned, unharvested
    """, rule="TPL007")
    assert len(fs) == 1 and "Engine.preempt_request" in fs[0].message


def test_tpl007_silent_when_harvested_first(tmp_path):
    # the exact shape LLMEngine.abort/step use: harvest (or a guarded
    # harvest) strictly before the first page-state mutation, including
    # mutations reached through a callee (step -> _admit)
    fs = lint_snippet(tmp_path, """
        class Engine:
            def _dispatch(self):
                self._inflight = {"out": 1}

            def _harvest(self, finished):
                self._inflight = None

            def _admit(self):
                row = self.cache.allocate_prefixed(0, 4, None)

            def abort(self, rid):
                if self._inflight is not None:
                    self._harvest([])
                self.cache.release(rid)
                return True

            def step(self):
                self._harvest([])
                self._admit()
    """, rule="TPL007")
    assert fs == []


def test_tpl007_silent_without_double_buffering(tmp_path):
    # no `_inflight` publication = no in-flight batch to corrupt: a
    # synchronous engine may mutate page state freely
    fs = lint_snippet(tmp_path, """
        class Engine:
            def abort(self, rid):
                self.cache.release(rid)
                return True
    """, rule="TPL007")
    assert fs == []


# ---------------------------------------------------------------------------
# suppression machinery
# ---------------------------------------------------------------------------

def test_suppression_with_reason_silences_and_is_recorded(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax

        def probe():
            try:
                return jax.devices()
            # tpu-lint: disable=TPL006 -- probe is best-effort by design
            except Exception:
                return []
    """)
    t6 = [f for f in fs if f.rule == "TPL006"]
    assert len(t6) == 1 and t6[0].suppressed
    assert t6[0].reason == "probe is best-effort by design"
    assert [f for f in fs if f.rule == "LINT000"] == []


def test_suppression_without_reason_is_lint000_and_ignored(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax

        def probe():
            try:
                return jax.devices()
            # tpu-lint: disable=TPL006
            except Exception:
                return []
    """)
    assert any(f.rule == "LINT000" for f in fs)
    t6 = [f for f in fs if f.rule == "TPL006"]
    assert len(t6) == 1 and not t6[0].suppressed   # disable had no effect


def test_suppression_syntax_inside_docstring_is_inert(tmp_path):
    """Documentation that QUOTES the disable syntax (a docstring, a string
    literal) must not become a live suppression — only real comments count."""
    fs = lint_snippet(tmp_path, '''
        """Docs: suppress with `# tpu-lint: disable-file=TPL006 -- reason`."""
        import jax

        def probe():
            try:
                return jax.devices()
            except Exception:
                return []
    ''')
    t6 = [f for f in fs if f.rule == "TPL006"]
    assert len(t6) == 1 and not t6[0].suppressed


def test_file_wide_suppression(tmp_path):
    fs = lint_snippet(tmp_path, """
        # tpu-lint: disable-file=TPL006 -- generated bindings, audited upstream
        import jax

        def probe():
            try:
                return jax.devices()
            except Exception:
                return []
    """)
    t6 = [f for f in fs if f.rule == "TPL006"]
    assert len(t6) == 1 and t6[0].suppressed


# ---------------------------------------------------------------------------
# jaxpr level
# ---------------------------------------------------------------------------

def test_jxp001_transfer_inside_program():
    bad = jax.jit(lambda x: jax.device_put(x) + 1)
    good = jax.jit(lambda x: x + 1)
    args = (jnp.ones((4,), jnp.float32),)
    assert any(f.rule == "JXP001" for f in audit_jaxpr("bad", bad, args))
    assert audit_jaxpr("good", good, args) == []


def test_jxp002_undonated_declared_buffer():
    """The deliberately non-donated toy jit: a pool-style dict arg declared
    donated must arrive donated in the pjit params."""
    pool = {"k": jnp.zeros((64, 64), jnp.float32)}
    args = (pool, jnp.ones((), jnp.float32))

    def body(pool, x):
        return {k: v + x for k, v in pool.items()}, x * 2

    bad = jax.jit(body)
    fs = audit_jaxpr("bad", bad, args, donate_paths=("arg0",))
    assert any(f.rule == "JXP002" and "NOT donated" in f.message for f in fs)

    good = jax.jit(body, donate_argnums=(0,))
    assert audit_jaxpr("good", good, args, donate_paths=("arg0",)) == []


def test_jxp002_fails_closed_on_unjitted_callable():
    """A declared donation contract on a callable that never produces a pjit
    eqn (not actually jitted) must be reported, not silently skipped."""
    args = (jnp.zeros((8, 8), jnp.float32),)
    fs = audit_jaxpr("bad", lambda pool: pool * 2, args,
                     donate_paths=("arg0",))
    assert any(f.rule == "JXP002" and "cannot be audited" in f.message
               for f in fs)


def test_jxp002_donated_persistent_buffer_flagged():
    args = (jnp.zeros((8, 8), jnp.float32), jnp.ones((), jnp.float32))
    fn = jax.jit(lambda params, x: params * x, donate_argnums=(0,))
    fs = audit_jaxpr("bad", fn, args, keep_paths=("arg0",))
    assert any(f.rule == "JXP002" and "IS donated" in f.message for f in fs)


def test_jxp003_f64_upcast_flagged():
    from jax.experimental import enable_x64
    args = (jnp.ones((4,), jnp.float32),)
    with enable_x64():
        fs = audit_jaxpr("bad", jax.jit(lambda x: x.astype("float64")), args)
    assert any(f.rule == "JXP003" for f in fs)
    assert audit_jaxpr("good", jax.jit(lambda x: x * 2), args) == []


def test_jxp004_sharding_constraint_required_under_mp():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    import jax.sharding as jsh
    mesh = jsh.Mesh(np.array(jax.devices()[:2]), ("mp",))
    repl = jsh.NamedSharding(mesh, jsh.PartitionSpec())
    args = (jnp.ones((4,), jnp.float32),)
    good = jax.jit(
        lambda x: jax.lax.with_sharding_constraint(x + 1, repl))
    bad = jax.jit(lambda x: x + 1)
    assert audit_jaxpr("good", good, args,
                       require_sharding_constraint=True) == []
    fs = audit_jaxpr("bad", bad, args, require_sharding_constraint=True)
    assert any(f.rule == "JXP004" for f in fs)


def test_jxp005_oversized_host_output():
    """JXP005 pos/neg pair: a program returning [B, V] float logits (or any
    output blob over the int budget) is flagged; a token/accept-sized int
    output with the donated pool riding through passes."""
    B, V = 4, 256
    pool = {"k": jnp.zeros((8, 64), jnp.float32)}
    args = (pool, jnp.zeros((B, 5), jnp.int32))

    def bad_body(pool, tokens):
        logits = jnp.ones((B, V), jnp.float32) * tokens[:, :1]
        return logits, {k: v + 1 for k, v in pool.items()}

    fs = audit_jaxpr("bad", jax.jit(bad_body, donate_argnums=(0,)), args,
                     donate_paths=("arg0",), host_output_budget=B * 8)
    assert any(f.rule == "JXP005" and "logits" in f.message for f in fs)
    assert any(f.rule == "JXP005" and "budget" in f.message for f in fs)

    def bf16_body(pool, tokens):
        # bf16 logprobs SMALL enough to fit the element budget: the
        # float-matrix check alone must catch it (TPU serving dtype)
        lp = jnp.ones((B, 5), jnp.bfloat16) * tokens[:, :1].astype(jnp.bfloat16)
        return lp, {k: v + 1 for k, v in pool.items()}

    fs = audit_jaxpr("bad16", jax.jit(bf16_body, donate_argnums=(0,)), args,
                     donate_paths=("arg0",), host_output_budget=B * 8)
    assert any(f.rule == "JXP005" and "logits" in f.message for f in fs)

    def good_body(pool, tokens):
        preds = jnp.argmax(jnp.ones((B, 5, V)) * tokens[..., None], -1)
        return preds.astype(jnp.int32), jnp.zeros((B,), jnp.int32), \
            {k: v + 1 for k, v in pool.items()}

    assert audit_jaxpr("good", jax.jit(good_body, donate_argnums=(0,)), args,
                       donate_paths=("arg0",),
                       host_output_budget=B * 8) == []


def test_serving_executables_jaxpr_clean():
    """Level 2 over the REAL serving set (the fused one-dispatch step with
    its O(B*K)-int host-output budget, plus the --no-fuse decode/chunk/
    bucketed-prefill/verify trio and the COW copy, mp1 + mp2): donation
    declared == donation traced, no embedded transfers, no f64, mp outputs
    pinned, no logits-shaped host output."""
    assert run_jaxpr_checks(include_mp=True) == []


# ---------------------------------------------------------------------------
# repo-clean + CLI
# ---------------------------------------------------------------------------

def test_repo_inference_package_lints_clean():
    fs = run_ast_checks([os.path.join(REPO, "paddle_tpu", "inference")])
    assert [f.format() for f in fs if not f.suppressed] == []


def test_repo_wide_ast_lint_clean():
    fs = run_ast_checks([os.path.join(REPO, "paddle_tpu"),
                         os.path.join(REPO, "tools"),
                         os.path.join(REPO, "bench_serve.py")])
    assert [f.format() for f in fs if not f.suppressed] == []


def test_cli_exits_nonzero_on_fixture_and_zero_on_clean(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n"
                   "def probe():\n"
                   "    try:\n"
                   "        return jax.devices()\n"
                   "    except Exception:\n"
                   "        return []\n")
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")
    tool = os.path.join(REPO, "tools", "tpu_lint.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, tool, "--level", "ast", str(bad)],
                       capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 1 and "TPL006" in r.stdout
    r = subprocess.run([sys.executable, tool, "--level", "ast", str(clean)],
                       capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 0
    # a typo'd path must not report "clean": lint-nothing is a config error
    r = subprocess.run([sys.executable, tool, "--level", "ast",
                        "paddle_tpu/inferenec"],
                       capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 2 and "no such path" in r.stderr
    # ...and so is an existing path that yields zero python files
    empty = tmp_path / "empty"
    empty.mkdir()
    r = subprocess.run([sys.executable, tool, "--level", "ast", str(empty)],
                       capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 2 and "no python files" in r.stderr
