"""Local multi-process cluster tests (the reference TestDistBase pattern,
`test/legacy_test/test_dist_base.py:962` + `test/collective/` scripts).

Spawns real trainer processes through the launch CLI
(`python -m paddle_tpu.distributed.launch`), each of which brings up
jax.distributed on the CPU backend and runs eager collectives / DataParallel
across process boundaries — the multi-process path that single-process
virtual-mesh tests cannot exercise.
"""
import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

# slow tier: each test spawns a real multi-process cluster (launch CLI +
# jax.distributed bring-up, 10-30 s apiece, ~75 s for the module) — and on
# CPU-only jaxlib, which ships no cross-process collectives, they can only
# fail (as at seed; see CHANGES PR 1).  The tier-1 budget (ROADMAP, 870 s)
# is for the fast gate; run these via `-m slow` on a backend with real
# cross-process collectives.
pytestmark = pytest.mark.slow

SCRIPTS = os.path.join(os.path.dirname(__file__), "dist_scripts")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(script, nproc, tmp_path, timeout=240, env_extra=None):
    env = dict(os.environ)
    env.update(env_extra or {})
    env["PADDLE_DIST_DEVICE"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    log_dir = str(tmp_path / "logs")
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", str(nproc), "--log_dir", log_dir,
           os.path.join(SCRIPTS, script)]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout, cwd=str(tmp_path))
    logs = {}
    if os.path.isdir(log_dir):
        for f in sorted(os.listdir(log_dir)):
            with open(os.path.join(log_dir, f), errors="replace") as fh:
                logs[f] = fh.read()
    return proc, logs


def test_collectives_across_two_processes(tmp_path):
    proc, logs = _launch("collective_checks.py", 2, tmp_path)
    joined = "\n".join(f"--- {k}\n{v}" for k, v in logs.items())
    assert proc.returncode == 0, f"launch rc={proc.returncode}\n{proc.stdout}\n{joined}"
    for r in range(2):
        assert f"RANK {r} COLLECTIVES OK" in joined, joined


def test_collectives_across_four_processes(tmp_path):
    # 4 ranks: alltoall over 4, and a 2-of-4 subset send/recv pair (0 -> 3)
    proc, logs = _launch("collective_checks.py", 4, tmp_path)
    joined = "\n".join(f"--- {k}\n{v}" for k, v in logs.items())
    assert proc.returncode == 0, f"launch rc={proc.returncode}\n{proc.stdout}\n{joined}"
    for r in range(4):
        assert f"RANK {r} COLLECTIVES OK" in joined, joined


def test_dataparallel_loss_parity_vs_serial(tmp_path):
    proc, logs = _launch("dp_parity.py", 2, tmp_path)
    joined = "\n".join(logs.values())
    assert proc.returncode == 0, f"launch rc={proc.returncode}\n{proc.stdout}\n{joined}"
    results = [json.loads(m) for m in re.findall(r"DPRESULT (.*)", joined)]
    assert len(results) == 2, joined

    # serial reference: same script's run() with world=1 in-process
    sys.path.insert(0, SCRIPTS)
    try:
        import dp_parity
        serial_losses, serial_ps = dp_parity.run(1, 0)
    finally:
        sys.path.pop(0)

    # params after averaged-grad DP steps must match the full-batch serial run
    for r in results:
        np.testing.assert_allclose(r["param_sum"], serial_ps, rtol=1e-4)
    # both ranks hold identical params (grads were synced)
    np.testing.assert_allclose(results[0]["param_sum"], results[1]["param_sum"],
                               rtol=1e-6)
    # per-rank shard losses average to ~the serial full-batch loss at step 0
    # (identical params, disjoint equal shards)
    step0 = (results[0]["losses"][0] + results[1]["losses"][0]) / 2
    np.testing.assert_allclose(step0, serial_losses[0], rtol=1e-4)


@pytest.mark.parametrize("offload", ["0", "1"], ids=["hbm", "offload"])
def test_group_sharded_stage3_parity_and_memory(tmp_path, offload):
    """ZeRO-3 eager: loss parity vs serial AND ~world-x resident param
    shrinkage, with and without host offload (ref group_sharded_stage3)."""
    proc, logdict = _launch("stage3_parity.py", 2, tmp_path,
                            env_extra={"STAGE3_OFFLOAD": offload})
    logs = "\n".join(logdict.values())
    assert proc.returncode == 0, f"rc={proc.returncode}\n{proc.stdout}\n{logs}"
    results = [json.loads(m) for m in re.findall(r"S3RESULT (.*)", logs)]
    assert len(results) == 2, logs

    sys.path.insert(0, SCRIPTS)
    try:
        import stage3_parity
        serial_losses, serial_ps, _, _ = stage3_parity.run(1, 0, False)
    finally:
        sys.path.pop(0)

    for r in results:
        np.testing.assert_allclose(r["losses"], serial_losses, rtol=1e-4)
        np.testing.assert_allclose(r["param_sum"], serial_ps, rtol=1e-4)
        # resident bytes shrink ~2x (padding allows slack)
        assert r["resident_bytes"] < 0.75 * r["full_bytes"]


def test_tensor_parallel_mpu_across_processes(tmp_path):
    """Eager TP (VocabParallelEmbedding + Column/RowParallelLinear) across 2
    real processes: loss and grad shards match the serial model (ref
    hybrid_parallel_mp_model.py)."""
    proc, logdict = _launch("tp_parity.py", 2, tmp_path)
    logs = "\n".join(logdict.values())
    assert proc.returncode == 0, f"rc={proc.returncode}\n{proc.stdout}\n{logs}"
    results = [json.loads(m) for m in re.findall(r"TPRESULT (.*)", logs)]
    assert len(results) == 2, logs
    for r in results:
        np.testing.assert_allclose(r["loss"], r["serial_loss"], rtol=1e-4)
        assert r["grad_ok"], r
