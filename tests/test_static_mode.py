"""paddle.static executable surface (ref static Program/Executor over
ProgramDesc; book test pattern test/book/test_fit_a_line.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _build_linreg():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 2])
        y = static.data("y", [8, 1])
        w = static.create_parameter([2, 1], "float32", name="w")
        b = static.create_parameter([1], "float32", name="b", is_bias=True)
        pred = paddle.matmul(x, w) + b
        loss = paddle.mean((pred - y) ** 2)
    return main, startup, x, y, w, b, pred, loss


def test_fit_a_line_trains():
    """The book test: linear regression to near-zero loss via Executor.run."""
    main, startup, x, y, w, b, pred, loss = _build_linreg()
    with static.program_guard(main, startup):
        opt = paddle.optimizer.SGD(learning_rate=0.2, parameters=[w, b])
        opt.minimize(loss)

    rng = np.random.RandomState(0)
    X = rng.randn(8, 2).astype(np.float32)
    Y = (X @ np.array([[1.5], [-2.0]]) + 0.3).astype(np.float32)

    exe = static.Executor()
    exe.run(startup)
    losses = []
    for _ in range(60):
        (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < 1e-2, losses[::20]
    np.testing.assert_allclose(np.asarray(w._data).ravel(), [1.5, -2.0],
                               atol=0.05)


def test_executor_feed_substitution_no_train():
    main, startup, x, y, w, b, pred, loss = _build_linreg()
    exe = static.Executor()
    X1 = np.ones((8, 2), np.float32)
    X2 = np.full((8, 2), 2.0, np.float32)
    Y = np.zeros((8, 1), np.float32)
    (p1,) = exe.run(main, feed={"x": X1, "y": Y}, fetch_list=[pred])
    (p2,) = exe.run(main, feed={"x": X2, "y": Y}, fetch_list=[pred])
    np.testing.assert_allclose(p2, 2 * p1, rtol=1e-5)


def test_clone_for_test_drops_train_ops():
    main, startup, x, y, w, b, pred, loss = _build_linreg()
    with static.program_guard(main, startup):
        opt = paddle.optimizer.SGD(learning_rate=0.2, parameters=[w, b])
        opt.minimize(loss)
    test_prog = main.clone(for_test=True)
    assert all(op[0] == "op" for op in test_prog.ops)
    w0 = np.asarray(w._data).copy()
    exe = static.Executor()
    exe.run(test_prog, feed={"x": np.ones((8, 2), np.float32),
                             "y": np.ones((8, 1), np.float32)},
            fetch_list=[loss])
    np.testing.assert_allclose(np.asarray(w._data), w0)  # no update happened


def test_gradients_and_append_backward():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [3])
        z = (x * x).sum()
        g = static.gradients([z], [x])
    assert g[0] is not None


def test_save_load_inference_model(tmp_path):
    main, startup, x, y, w, b, pred, loss = _build_linreg()
    import jax.numpy as jnp
    w._data = jnp.asarray(np.array([[2.0], [3.0]], np.float32))
    path = str(tmp_path / "linreg")
    exe = static.Executor()
    static.save_inference_model(path, [x], [pred], exe, program=main)
    w._data = jnp.zeros_like(w._data)  # clobber, then reload
    prog, feed_names, fetches = static.load_inference_model(path, exe)
    np.testing.assert_allclose(np.asarray(w._data).ravel(), [2.0, 3.0])
    X = np.ones((8, 2), np.float32)
    (out,) = exe.run(prog, feed={"x": X, "y": np.zeros((8, 1), np.float32)},
                     fetch_list=fetches)
    np.testing.assert_allclose(out, X @ [[2.0], [3.0]] + np.asarray(b._data),
                               rtol=1e-5)


def test_program_state_roundtrip(tmp_path):
    main, startup, x, y, w, b, pred, loss = _build_linreg()
    import jax.numpy as jnp
    w._data = jnp.asarray(np.array([[7.0], [8.0]], np.float32))
    path = str(tmp_path / "m")
    static.save(main, path)
    w._data = jnp.zeros_like(w._data)
    static.load(main, path)
    np.testing.assert_allclose(np.asarray(w._data).ravel(), [7.0, 8.0])


def test_ema_and_scope():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        w = static.create_parameter([2], "float32", name="wv")
        ema = static.ExponentialMovingAverage(decay=0.5)
        import jax.numpy as jnp
        w._data = jnp.asarray([1.0, 1.0])
        ema.update()
        w._data = jnp.asarray([3.0, 3.0])
        ema.update()
        with ema.apply():
            np.testing.assert_allclose(np.asarray(w._data), [2.0, 2.0])
        np.testing.assert_allclose(np.asarray(w._data), [3.0, 3.0])
        v = static.global_scope().find_var("wv")
        assert v is not None and v.get_tensor().shape == (2,)


def test_load_inference_model_cross_process(tmp_path):
    """Registry cleared => the StableHLO artifact alone must serve."""
    main, startup, x, y, w, b, pred, loss = _build_linreg()
    import jax.numpy as jnp
    w._data = jnp.asarray(np.array([[2.0], [3.0]], np.float32))
    path = str(tmp_path / "xproc")
    exe = static.Executor()
    static.save_inference_model(path, [x], [pred], exe, program=main)
    static._inference_registry.clear()   # simulate a fresh process
    prog, feed_names, fetches = static.load_inference_model(path, exe)
    X = np.ones((8, 2), np.float32)
    (out,) = exe.run(prog, feed={"x": X}, fetch_list=fetches)
    np.testing.assert_allclose(out, X @ [[2.0], [3.0]] + np.asarray(b._data),
                               rtol=1e-5)


def test_feed_resize_across_runs():
    """Placeholder grads must not leak across runs (batch size change)."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 4])
        w = static.create_parameter([4, 1], "float32", name="w2")
        loss = paddle.mean(paddle.matmul(x, w))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(main, feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[loss])
    exe.run(main, feed={"x": np.ones((3, 4), np.float32)}, fetch_list=[loss])
