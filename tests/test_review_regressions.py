"""Regression tests for review findings (round 1 code review)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def t(a, sg=False):
    return paddle.to_tensor(np.asarray(a, np.float32), stop_gradient=sg)


def test_backward_through_multioutput_with_unused_int_output():
    # topk returns (values, int indices); unused indices must not break backward
    x = t([[3.0, 1.0, 2.0]])
    vals, idx = paddle.topk(x, k=2)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1.0, 0.0, 1.0]])
    # kthvalue too
    x2 = t([[3.0, 1.0, 2.0]])
    v, i = paddle.kthvalue(x2, 2)
    v.sum().backward()
    assert x2.grad is not None


def test_setitem_into_stop_gradient_tensor_keeps_graph():
    y = paddle.zeros([4])
    assert y.stop_gradient
    x = t([5.0])
    y[0] = x
    assert not y.stop_gradient
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0])


def test_adamw_zero_weight_decay_int():
    p = t([1.0])
    opt = paddle.optimizer.AdamW(learning_rate=0.0, parameters=[p], weight_decay=0)
    assert opt._coeff == 0.0
    (p * p).sum().backward()
    before = p.numpy().copy()
    opt.step()
    np.testing.assert_allclose(p.numpy(), before)  # lr=0, wd=0 -> no movement


def test_tensor_T_reverses_all_dims():
    x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert x.T.shape == [4, 3, 2]
    assert x.mT.shape == [2, 4, 3]
    np.testing.assert_allclose(x.T.numpy(), x.numpy().T)


def test_clear_grad_set_to_zero():
    p = t([2.0])
    (p * p).backward()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    opt.clear_grad(set_to_zero=True)
    assert p.grad is not None
    np.testing.assert_allclose(p.grad.numpy(), [0.0])
    opt.clear_grad(set_to_zero=False)
    assert p.grad is None


def test_grad_allow_unused_raises():
    x = t([1.0])
    y = t([1.0])
    z = x * 2
    with pytest.raises(ValueError):
        paddle.grad(z, [x, y], allow_unused=False)
    z = x * 2  # fresh graph: the failed call above consumed the old tape
    gx, gy = paddle.grad(z, [x, y], allow_unused=True)
    assert gy is None
    np.testing.assert_allclose(gx.numpy(), [2.0])


def test_retain_grads_on_intermediate():
    x = t([2.0])
    h = x * x
    h.retain_grads()
    z = h * 3.0
    z.backward()
    assert h.grad is not None
    np.testing.assert_allclose(h.grad.numpy(), [3.0])
    np.testing.assert_allclose(x.grad.numpy(), [12.0])


def test_nonleaf_hook_fires():
    x = t([2.0])
    h = x * x
    seen = []
    h.register_hook(lambda g: seen.append(g.numpy().copy()))
    (h * 3.0).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [3.0])


def test_lbfgs_converges_on_quadratic():
    x = t(np.array([5.0, -3.0]))
    x.persistable = True
    opt = paddle.optimizer.LBFGS(learning_rate=0.5, parameters=[x])

    def closure():
        opt.clear_grad(set_to_zero=False)
        loss = ((x - 1.0) ** 2).sum()
        loss.backward()
        return loss

    for _ in range(20):
        loss = opt.step(closure)
    assert float(loss.numpy()) < 1e-3
