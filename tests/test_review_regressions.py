"""Regression tests for review findings (round 1 code review)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def t(a, sg=False):
    return paddle.to_tensor(np.asarray(a, np.float32), stop_gradient=sg)


def test_backward_through_multioutput_with_unused_int_output():
    # topk returns (values, int indices); unused indices must not break backward
    x = t([[3.0, 1.0, 2.0]])
    vals, idx = paddle.topk(x, k=2)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1.0, 0.0, 1.0]])
    # kthvalue too
    x2 = t([[3.0, 1.0, 2.0]])
    v, i = paddle.kthvalue(x2, 2)
    v.sum().backward()
    assert x2.grad is not None


def test_setitem_into_stop_gradient_tensor_keeps_graph():
    y = paddle.zeros([4])
    assert y.stop_gradient
    x = t([5.0])
    y[0] = x
    assert not y.stop_gradient
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0])


def test_adamw_zero_weight_decay_int():
    p = t([1.0])
    opt = paddle.optimizer.AdamW(learning_rate=0.0, parameters=[p], weight_decay=0)
    assert opt._coeff == 0.0
    (p * p).sum().backward()
    before = p.numpy().copy()
    opt.step()
    np.testing.assert_allclose(p.numpy(), before)  # lr=0, wd=0 -> no movement


def test_tensor_T_reverses_all_dims():
    x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert x.T.shape == [4, 3, 2]
    assert x.mT.shape == [2, 4, 3]
    np.testing.assert_allclose(x.T.numpy(), x.numpy().T)


def test_clear_grad_set_to_zero():
    p = t([2.0])
    (p * p).backward()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    opt.clear_grad(set_to_zero=True)
    assert p.grad is not None
    np.testing.assert_allclose(p.grad.numpy(), [0.0])
    opt.clear_grad(set_to_zero=False)
    assert p.grad is None


def test_grad_allow_unused_raises():
    x = t([1.0])
    y = t([1.0])
    z = x * 2
    with pytest.raises(ValueError):
        paddle.grad(z, [x, y], allow_unused=False)
    z = x * 2  # fresh graph: the failed call above consumed the old tape
    gx, gy = paddle.grad(z, [x, y], allow_unused=True)
    assert gy is None
    np.testing.assert_allclose(gx.numpy(), [2.0])


def test_retain_grads_on_intermediate():
    x = t([2.0])
    h = x * x
    h.retain_grads()
    z = h * 3.0
    z.backward()
    assert h.grad is not None
    np.testing.assert_allclose(h.grad.numpy(), [3.0])
    np.testing.assert_allclose(x.grad.numpy(), [12.0])


def test_nonleaf_hook_fires():
    x = t([2.0])
    h = x * x
    seen = []
    h.register_hook(lambda g: seen.append(g.numpy().copy()))
    (h * 3.0).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [3.0])


def test_lbfgs_converges_on_quadratic():
    x = t(np.array([5.0, -3.0]))
    x.persistable = True
    opt = paddle.optimizer.LBFGS(learning_rate=0.5, parameters=[x])

    def closure():
        opt.clear_grad(set_to_zero=False)
        loss = ((x - 1.0) ** 2).sum()
        loss.backward()
        return loss

    for _ in range(20):
        loss = opt.step(closure)
    assert float(loss.numpy()) < 1e-3


# ---- round-2 advisor findings ----

def test_recompute_trains_wrapped_layer_params():
    # advisor(high): recompute() must differentiate layer params, not just args
    from paddle_tpu.distributed.fleet.recompute import recompute
    lin = nn.Linear(4, 4)
    x = t(np.random.RandomState(0).randn(2, 4))
    out = recompute(lin, x)
    out.sum().backward()
    assert lin.weight.grad is not None and lin.bias.grad is not None
    assert x.grad is not None
    # parity with plain forward
    lin2 = nn.Linear(4, 4)
    lin2.set_state_dict(lin.state_dict())
    x2 = t(x.numpy())
    lin2(x2).sum().backward()
    np.testing.assert_allclose(lin.weight.grad.numpy(), lin2.weight.grad.numpy(),
                               rtol=1e-5)


def test_recompute_closure_function_params():
    from paddle_tpu.distributed.fleet.recompute import recompute
    lin = nn.Linear(3, 3)

    def fn(x):
        return paddle.nn.functional.relu(lin(x))

    x = t(np.random.RandomState(1).randn(2, 3))
    recompute(fn, x).sum().backward()
    assert lin.weight.grad is not None


def test_grad_scaler_unscale_then_step_not_double_unscaled():
    # advisor(high): unscale_ + clip + step must not unscale twice
    p = t([1.0])
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
    loss = (p * 2.0).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)
    np.testing.assert_allclose(p.grad.numpy(), [2.0], rtol=1e-6)
    scaler.step(opt)
    # update must be grad * lr = 2.0, not 2.0/1024
    np.testing.assert_allclose(p.numpy(), [-1.0], rtol=1e-5)
    # calling unscale_ twice before step raises
    p.clear_grad()
    loss2 = (p * 2.0).sum()
    scaler.scale(loss2).backward()
    scaler.unscale_(opt)
    with pytest.raises(RuntimeError):
        scaler.unscale_(opt)


def test_grad_scaler_minimize_after_explicit_backward():
    # advisor(medium): reference pattern scaled.backward(); scaler.minimize(...)
    p = t([1.0])
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
    scaled = scaler.scale((p * 3.0).sum())
    scaled.backward()
    scaler.minimize(opt, scaled)  # must NOT re-run backward
    np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 3.0], rtol=1e-5)


def test_optimizer_minimize_after_explicit_backward():
    p = t([2.0])
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    loss = (p * p).sum()
    loss.backward()
    opt.minimize(loss)  # tape consumed: collect existing grads, no second backward
    np.testing.assert_allclose(p.numpy(), [2.0 - 0.1 * 4.0], rtol=1e-5)


def test_create_graph_second_order():
    # advisor(medium): double backward — d2/dx2 of x**3 = 6x
    x = t([2.0, 3.0])
    y = (x ** 3).sum()
    (g,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(g.numpy(), [12.0, 27.0], rtol=1e-5)
    (g2,) = paddle.grad(g.sum(), [x])
    np.testing.assert_allclose(g2.numpy(), [12.0, 18.0], rtol=1e-5)


def test_create_graph_gradient_penalty():
    # WGAN-GP style: backward through a grad-norm penalty reaches the leaf
    x = t([1.0, 2.0])
    w = t([3.0, 4.0])
    y = (w * x * x).sum()
    (gx,) = paddle.grad(y, [x], create_graph=True)  # 2*w*x
    penalty = (gx * gx).sum()
    penalty.backward()
    # d/dw of (2*w*x)^2 = 8*w*x^2
    np.testing.assert_allclose(w.grad.numpy(), [8.0 * 3.0 * 1.0, 8.0 * 4.0 * 4.0],
                               rtol=1e-5)


def test_save_format_plain_ndarray_interop():
    # advisor(low): checkpoints are plain {name: ndarray} pickles like the reference
    import pickle, tempfile, os
    lin = nn.Linear(2, 2)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.pdparams")
        paddle.save(lin.state_dict(), path)
        with open(path, "rb") as f:
            raw = pickle.load(f)
        assert all(isinstance(v, np.ndarray) for v in raw.values()), raw
        # reference-produced checkpoints (plain ndarray dicts) load as Tensors
        loaded = paddle.load(path)
        assert all(hasattr(v, "numpy") for v in loaded.values())
        lin.set_state_dict(loaded)


def test_recompute_sequential_trains_params():
    # review: closure holds a plain list of layers — params must still be found
    from paddle_tpu.distributed.fleet.recompute import recompute_sequential
    layers = [nn.Linear(3, 3), nn.Linear(3, 3)]
    x = t(np.random.RandomState(2).randn(2, 3))
    out = recompute_sequential({"segments": 2}, layers, x)
    out.sum().backward()
    for l in layers:
        assert l.weight.grad is not None


def test_grad_scaler_per_optimizer_unscale_state():
    # review: one scaler, two optimizers (GAN pattern) — independent unscale state
    pg, pd = t([1.0]), t([1.0])
    og = paddle.optimizer.SGD(learning_rate=1.0, parameters=[pg])
    od = paddle.optimizer.SGD(learning_rate=1.0, parameters=[pd])
    scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
    scaler.scale((pg * 2.0).sum() + (pd * 3.0).sum()).backward()
    scaler.unscale_(og)
    scaler.unscale_(od)  # must NOT raise: od was never unscaled
    scaler.step(og)
    scaler.step(od)
    np.testing.assert_allclose(pg.numpy(), [-1.0], rtol=1e-5)
    np.testing.assert_allclose(pd.numpy(), [-2.0], rtol=1e-5)


def test_minimize_after_backward_retain_graph_no_double_grad():
    p = t([2.0])
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    loss = (p * p).sum()
    loss.backward(retain_graph=True)
    opt.minimize(loss)  # tape still live, but backward already ran: no re-run
    np.testing.assert_allclose(p.numpy(), [2.0 - 0.1 * 4.0], rtol=1e-5)


def test_grad_scaler_per_optimizer_found_inf():
    # review r2: one optimizer sees inf grads, the other finite — inf one must
    # be skipped, finite one stepped, regardless of unscale_ ordering
    pg, pd = t([1.0]), t([1.0])
    og = paddle.optimizer.SGD(learning_rate=1.0, parameters=[pg])
    od = paddle.optimizer.SGD(learning_rate=1.0, parameters=[pd])
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
    scaler.scale((pg * 2.0).sum() + (pd * 3.0).sum()).backward()
    pg.grad._data = pg.grad._data * np.inf  # poison G's grads
    scaler.unscale_(og)
    scaler.unscale_(od)  # must not clear og's inf status
    scaler.step(og)      # skipped: inf
    scaler.step(od)      # applied
    np.testing.assert_allclose(pg.numpy(), [1.0])
    np.testing.assert_allclose(pd.numpy(), [-2.0], rtol=1e-5)


def test_create_graph_replay_uses_forward_time_primals():
    # review r2: mutating a tensor between forward and create_graph backward must
    # not shift the linearization point
    x = t([2.0])
    w = t([3.0])
    y = (w * x * x).sum()
    w._data = w._data * 100.0  # in-place mutation after forward
    (gx,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(gx.numpy(), [12.0], rtol=1e-5)  # 2*w_orig*x
    (gxx,) = paddle.grad(gx.sum(), [x], allow_unused=True)
    np.testing.assert_allclose(gxx.numpy(), [6.0], rtol=1e-5)  # 2*w_orig


# ---- round-3 ADVICE fixes ----

def test_checkpoint_bf16_roundtrip(tmp_path):
    import jax
    import jax.numpy as jnp
    import ml_dtypes
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    st = {"p": jnp.arange(8, dtype=jnp.bfloat16).reshape(2, 4),
          "q": jnp.ones((3,), jnp.float32)}
    save_state_dict(st, str(tmp_path / "ckpt"))
    out = load_state_dict(str(tmp_path / "ckpt"))
    assert out["p"].dtype == ml_dtypes.bfloat16
    assert out["q"].dtype == np.float32
    np.testing.assert_array_equal(out["p"].astype(np.float32),
                                  np.asarray(st["p"]).astype(np.float32))
    dev = jax.device_put(out["p"])  # must be a valid jax dtype again
    assert dev.dtype == jnp.bfloat16


def test_hsigmoid_custom_path():
    import paddle_tpu.nn.functional as F
    rng = np.random.RandomState(0)
    x = t(rng.randn(3, 5))
    w = t(rng.randn(6, 5))
    # sample paths through nodes, -1 padded
    pt = paddle.to_tensor(np.array([[0, 2, -1], [1, 3, 4], [0, -1, -1]],
                                   np.int64))
    pc = paddle.to_tensor(np.array([[1, 0, -1], [0, 1, 1], [0, -1, -1]],
                                   np.int64))
    loss = F.hsigmoid_loss(x, paddle.to_tensor(np.zeros((3, 1), np.int64)),
                           None, w, path_table=pt, path_code=pc)
    # numpy reference: BCE(sigmoid(w_n . x), code) summed over valid nodes
    xs, ws = x.numpy(), w.numpy()
    tot = 0.0
    for i in range(3):
        for j in range(3):
            n = int(pt.numpy()[i, j])
            if n < 0:
                continue
            z = float(ws[n] @ xs[i])
            c = int(pc.numpy()[i, j])
            tot += np.log1p(np.exp(-z)) if c else np.log1p(np.exp(z))
    np.testing.assert_allclose(float(loss.numpy()), tot / 3, rtol=1e-5)
    # mismatched pair raises
    with pytest.raises(ValueError):
        F.hsigmoid_loss(x, paddle.to_tensor(np.zeros((3, 1), np.int64)),
                        None, w, path_table=pt)


def test_margin_cross_entropy_group_raises():
    import paddle_tpu.nn.functional as F

    class FakeGroup:
        nranks = 2
    with pytest.raises(NotImplementedError):
        F.margin_cross_entropy(t(np.eye(3, 4)),
                               paddle.to_tensor(np.zeros((3,), np.int64)),
                               group=FakeGroup())


def test_dataparallel_callback_deregisters_on_death():
    from paddle_tpu.core import autograd as ag
    from paddle_tpu.distributed.parallel import DataParallel
    n0 = len(ag._post_backward_callbacks)
    m = nn.Linear(2, 2)
    dp = DataParallel(m)  # world=1 at construction (no distributed env)
    dp._world = 2
    dp._register_hooks()  # registers the post-backward callback for real
    assert len(ag._post_backward_callbacks) == n0 + 1
    # nothing reachable from the registry or the param hooks may strongly hold
    # the wrapper: a plain del must deregister by refcount alone (no gc pass)
    del dp
    assert len(ag._post_backward_callbacks) == n0
    # and a stale callback firing after wrapper death self-deregisters
    dp2 = DataParallel(m)
    dp2._world = 2
    dp2._register_hooks()
    cb = dp2._post_backward_cb
    del dp2  # __del__ removes the tracked registration
    ag._post_backward_callbacks.append(cb)  # simulate a leaked stale entry
    cb()  # dead weakref path: must self-deregister, not crash
    assert len(ag._post_backward_callbacks) == n0
