"""Disaggregated prefill/decode serving (ISSUE 17): the durable tier
index, engine-restart session restore, cross-engine page handoff through
the shared store, role-aware fleet routing, and the degrade paths.

The load-bearing bars:
- a FRESH engine on the same `spill_dir` re-attaches the serialized index
  at construction and restores a returning session with ONE
  `swap_in_pages` scatter (dispatch count asserted), byte-identical to a
  cold re-prefill oracle;
- corrupted or version-skewed index blobs and vanished page objects
  degrade to re-prefill — never a crash, never different tokens;
- an abort landing between `allocate_prefixed` and `take_restore` releases
  the un-consumed restore plan (`release` discards it; `check_invariants`
  partitions the survivors);
- a 1P:1D `EngineFleet` emits byte-exact greedy tokens vs the colocated
  single-engine oracle on the same multi-turn stream, with the
  `kv_handoff_*` counters moving and health role-labeled;
- `FaultPlan.fail_h2d` on the decode pool degrades every store restore to
  local re-prefill, parity-lossless.
"""
from __future__ import annotations

import json
import os

import numpy as np
import pytest

import jax

from paddle_tpu.inference.cache import (HostKVTier, PagedKVCache,
                                        TIER_INDEX_VERSION)
from paddle_tpu.inference.engine import LLMEngine
from paddle_tpu.inference.faults import FaultPlan
from paddle_tpu.models import gpt as G


@pytest.fixture(scope="module")
def cfg():
    return G.gpt_tiny(64)


@pytest.fixture(scope="module")
def params(cfg):
    return G.init_params(cfg, jax.random.key(0))


def _engine(params, cfg, **kw):
    base = dict(num_slots=2, page_size=8, num_pages=9, max_model_len=64,
                prefill_chunk=16, seed=3, swap_pool_pages=64)
    base.update(kw)
    return LLMEngine(params, cfg, **base)


def _serve_and_export(params, cfg, spill_dir, rng_seed=7):
    """Engine A serves turn 1 on `spill_dir`, exports the conversation to
    the store, and is destroyed.  Returns (returning-turn prompt, the
    oracle's returning-turn tokens from a cold tier-less engine)."""
    rng = np.random.RandomState(rng_seed)
    prompt = rng.randint(0, cfg.vocab_size, (20,)).astype(np.int32)
    eng_a = _engine(params, cfg, spill_dir=spill_dir)
    out1 = eng_a.result(eng_a.add_request(prompt, max_new_tokens=5))
    conv = np.concatenate([prompt, np.asarray(out1.token_ids, np.int32)])
    exp = eng_a.export_prefix(conv)
    assert exp["pages"] > 0 and exp["index_nodes"] > 0
    eng_a.cache.check_invariants()
    del eng_a
    conv2 = np.concatenate([conv, rng.randint(0, cfg.vocab_size, (4,))
                            .astype(np.int32)])
    oracle = _engine(params, cfg)          # cold: pure re-prefill baseline
    ref = oracle.result(oracle.add_request(conv2, max_new_tokens=5))
    return conv2, list(ref.token_ids)


# ---------------------------------------------------------------------------
# engine restart: the durable index re-attaches, one scatter, byte parity
# ---------------------------------------------------------------------------

def test_restart_restores_with_one_scatter(params, cfg, tmp_path):
    """Kill an engine mid-conversation, construct a fresh one on the same
    spill_dir: the returning turn re-attaches the serialized index and
    restores with exactly ONE swap_in dispatch, tokens byte-identical to a
    cold re-prefill."""
    conv2, ref = _serve_and_export(params, cfg, str(tmp_path))
    eng_b = _engine(params, cfg, spill_dir=str(tmp_path))
    assert eng_b._store_restored_nodes > 0     # index re-attached at init
    calls = []
    orig = eng_b._swap_in_fn

    def counting(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    eng_b._swap_in_fn = counting
    out = eng_b.result(eng_b.add_request(conv2, max_new_tokens=5))
    eng_b._swap_in_fn = orig
    assert list(out.token_ids) == ref
    assert len(calls) == 1, f"restore took {len(calls)} scatters, not 1"
    st = eng_b.stats()
    assert st["kv_tier"]["restores"] == 1
    assert st["kv_tier"]["restored_tokens"] >= 16      # >= 2 full pages
    assert st["kv_tier"]["store_nodes_restored"] > 0
    # zero new compiled programs: restore rode the warmed swap bucket
    assert st["swap_executables"] <= 2
    eng_b.cache.check_invariants()


def test_corrupted_index_degrades_to_reprefill(params, cfg, tmp_path):
    """A truncated/garbage index blob imports nothing: the returning turn
    re-prefills and emits the same tokens — no crash, no drift."""
    conv2, ref = _serve_and_export(params, cfg, str(tmp_path))
    blobs = [f for f in os.listdir(str(tmp_path)) if f.startswith("kvindex_")]
    assert blobs
    for b in blobs:
        with open(os.path.join(str(tmp_path), b), "wb") as f:
            f.write(b"{corrupt json \xff\xfe")
    eng_b = _engine(params, cfg, spill_dir=str(tmp_path))
    assert eng_b._store_restored_nodes == 0
    out = eng_b.result(eng_b.add_request(conv2, max_new_tokens=5))
    assert list(out.token_ids) == ref
    assert eng_b.stats()["kv_tier"]["restores"] == 0
    eng_b.cache.check_invariants()


def test_version_skewed_index_is_ignored(params, cfg, tmp_path):
    """An index written by a future (or ancient) format version is skipped
    wholesale — restart degrades to re-prefill instead of misreading it."""
    conv2, ref = _serve_and_export(params, cfg, str(tmp_path))
    for b in os.listdir(str(tmp_path)):
        if not b.startswith("kvindex_"):
            continue
        path = os.path.join(str(tmp_path), b)
        with open(path) as f:
            doc = json.load(f)
        assert doc["version"] == TIER_INDEX_VERSION
        doc["version"] = 99
        with open(path, "w") as f:
            json.dump(doc, f)
    eng_b = _engine(params, cfg, spill_dir=str(tmp_path))
    assert eng_b._store_restored_nodes == 0
    out = eng_b.result(eng_b.add_request(conv2, max_new_tokens=5))
    assert list(out.token_ids) == ref
    eng_b.cache.check_invariants()


def test_missing_page_object_breaks_chain_not_engine(params, cfg, tmp_path):
    """Deleting a kvnode page object mid-chain imports only the ancestors
    that still resolve; the returning turn restores what survived and
    re-prefills the rest — same tokens."""
    conv2, ref = _serve_and_export(params, cfg, str(tmp_path))
    pages = sorted(f for f in os.listdir(str(tmp_path))
                   if f.startswith("kvnode_"))
    assert len(pages) >= 2
    os.remove(os.path.join(str(tmp_path), pages[1]))   # mid-chain object
    eng_b = _engine(params, cfg, spill_dir=str(tmp_path))
    assert 0 < eng_b._store_restored_nodes < len(pages)
    out = eng_b.result(eng_b.add_request(conv2, max_new_tokens=5))
    assert list(out.token_ids) == ref
    eng_b.cache.check_invariants()


# ---------------------------------------------------------------------------
# bugfix: abort while a tier-restore plan is pending
# ---------------------------------------------------------------------------

def test_release_discards_pending_restore_plan(tmp_path):
    """An abort landing between `allocate_prefixed` (which plans a tier
    restore) and `take_restore` must not strand the plan: `release`
    discards it, the planned nodes stay in the tier, and the
    `check_invariants` restore-plan partition stays green."""
    mgr = PagedKVCache(num_pages=9, page_size=4, num_slots=2,
                       max_pages_per_slot=8)
    tier = HostKVTier(spill_dir=str(tmp_path), disk_pages=64)
    mgr.attach_tier(tier, lambda nodes: {nd.node_id for nd in nodes})
    toks = np.arange(12, dtype=np.int32)
    mgr.allocate(0, 12)
    mgr.lengths[0] = 12
    mgr.register_prefix(0, toks, 12)
    mgr.release(0)
    # park the whole chain in the tier (the engine's accept bookkeeping)
    full, partial = mgr._match(toks)
    for nd in list(full) + [partial[0]]:
        mgr._lru.pop(nd.node_id)
        mgr._free.append(nd.page)
        del mgr._page_node[nd.page]
        nd.page = -1
        mgr._tier_nodes[nd.node_id] = nd
        tier.add_pending(nd.node_id)
        tier.fill(nd.node_id, {"k": np.zeros((4,), np.float32)})
    mgr.check_invariants()
    _, matched, _ = mgr.allocate_prefixed(0, 12, toks)
    assert matched > 0
    assert mgr._restore_plan.get(0), "admission should have planned a restore"
    mgr.check_invariants()          # plan pending for an allocated slot: ok
    mgr.release(0)                  # abort before take_restore
    assert not mgr._restore_plan, "release leaked the un-consumed plan"
    mgr.check_invariants()
    # the planned nodes are still tier-resident and still matchable
    _, matched2, _ = mgr.allocate_prefixed(1, 12, toks)
    assert matched2 == matched
    plan = mgr.take_restore(1)
    assert plan
    mgr.release(1)
    mgr.check_invariants()


def test_engine_abort_between_plan_and_restore(params, cfg, tmp_path):
    """Engine-level: aborting a queued request whose admission would have
    tier-restored leaves no stranded plan (drain invariants hold) and the
    session is still restorable afterwards."""
    conv2, ref = _serve_and_export(params, cfg, str(tmp_path))
    eng_b = _engine(params, cfg, spill_dir=str(tmp_path))
    rid = eng_b.add_request(conv2, max_new_tokens=5)
    eng_b.abort(rid)
    eng_b.cache.check_invariants()
    out = eng_b.result(eng_b.add_request(conv2, max_new_tokens=5))
    assert list(out.token_ids) == ref
    eng_b.cache.check_invariants()


# ---------------------------------------------------------------------------
# 1P:1D fleet: handoff parity + counters + role-labeled health
# ---------------------------------------------------------------------------

def test_disagg_fleet_parity_and_handoff_counters(params, cfg):
    """A 1P:1D fleet serves a 2-session x 2-turn stream byte-identically to
    one colocated engine, with prefill exports and decode tier-restores
    both visible in the counters and health labeled per role."""
    from paddle_tpu.inference.router import EngineFleet

    ekw = dict(num_slots=2, page_size=8, max_model_len=64,
               prefill_chunk=16, seed=3)
    rng = np.random.RandomState(9)
    prompts = [rng.randint(0, cfg.vocab_size, (18,)).astype(np.int32)
               for _ in range(2)]

    oracle = LLMEngine(params, cfg, **ekw)
    ref, convs = {}, [list(p) for p in prompts]
    for s in range(2):
        for t in range(2):
            o = oracle.result(oracle.add_request(
                np.asarray(convs[s], np.int32), max_new_tokens=5))
            ref[(s, t)] = list(o.token_ids)
            convs[s] = convs[s] + ref[(s, t)]

    fleet = EngineFleet(params, cfg, roles="P:D", engine_kwargs=dict(ekw))
    assert fleet.prefill_pool and fleet.decode_pool
    fleet.warm()
    convs = [list(p) for p in prompts]
    with fleet:
        for s in range(2):
            for t in range(2):
                h = fleet.submit(np.asarray(convs[s], np.int32),
                                 session=f"s{s}", max_new_tokens=5)
                out = fleet.result(h, timeout=120.0)
                assert out is not None
                assert list(out.token_ids) == ref[(s, t)], (s, t)
                convs[s] = convs[s] + list(out.token_ids)
        fleet.check_invariants()
        pe = fleet.engines[fleet.prefill_pool[0]]
        de = fleet.engines[fleet.decode_pool[0]]
        assert pe.stats()["kv_tier"]["handoff_exports"] >= 1
        assert pe.stats()["kv_tier"]["handoff_pages"] >= 1
        assert de.stats()["kv_tier"]["restores"] >= 1
        fst = fleet.stats()
        assert fst["disagg"]["handoffs"] >= 1
        assert fst["disagg"]["handoff_p99_ms"] > 0
        h = fleet.health()
        roles = {h["per_engine"][l]["role"] for l in fleet.prefill_pool}
        assert roles == {"prefill"}
        roles = {h["per_engine"][l]["role"] for l in fleet.decode_pool}
        assert roles == {"decode"}


def test_disagg_fail_h2d_degrades_to_local_reprefill(params, cfg, tmp_path):
    """Pre-built 1P:1D pools where every decode-side restore h2d fails:
    handoffs export fine, the decode engine drops each planned restore and
    re-prefills locally — tokens still byte-identical to the oracle."""
    from paddle_tpu.inference.router import EngineFleet

    ekw = dict(num_slots=2, page_size=8, max_model_len=64,
               prefill_chunk=16, seed=3, spill_dir=str(tmp_path))
    rng = np.random.RandomState(13)
    prompt = rng.randint(0, cfg.vocab_size, (20,)).astype(np.int32)

    oracle = LLMEngine(params, cfg, **dict(ekw, spill_dir=None))
    ref = list(oracle.result(oracle.add_request(
        prompt, max_new_tokens=5)).token_ids)

    pe = LLMEngine(params, cfg, role="prefill", **ekw)
    de = LLMEngine(params, cfg, role="decode",
                   fault_plan=FaultPlan(fail_h2d=1000), **ekw)
    fleet = EngineFleet(engines=[pe, de], roles="P:D")
    with fleet:
        h = fleet.submit(prompt, session="s0", max_new_tokens=5)
        out = fleet.result(h, timeout=120.0)
        assert out is not None
        assert list(out.token_ids) == ref
        fleet.check_invariants()
    assert pe.stats()["kv_tier"]["handoff_exports"] >= 1
    assert de.stats()["kv_tier"]["restores"] == 0      # every restore failed
