"""Expert parallelism end-to-end: ep mesh axis + all-to-all dispatch.

Reference parity targets:
- `incubate/distributed/models/moe/moe_layer.py` (capacity dispatch),
- `fluid/operators/collective/global_scatter_op.cc` / `global_gather_op.cc`
  (the all-to-all EP exchange, here `_moe_local` under shard_map),
- MoE wired into the GPT flagship via `GPTConfig.moe_num_experts`.

Runs on the 8-device virtual CPU mesh (tests/conftest.py).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models.gpt import gpt_moe_tiny
from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig


def _data(cfg, B=8, S=64, seed=0):
    rng = np.random.RandomState(seed)
    tok = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def _losses(tr, tok, lab, n=3):
    return [float(tr.train_step(tok, lab)) for _ in range(n)]


def _cfg_nodrop():
    # capacity_factor 8 => no token drops => ep/dense math is identical
    c = gpt_moe_tiny(64, num_experts=4, capacity_factor=8.0)
    c.moe_aux_weight = 0.0
    return c


def test_moe_dense_learns():
    cfg = gpt_moe_tiny(64, num_experts=4, capacity_factor=2.0)
    tok, lab = _data(cfg)
    tr = HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                               devices=jax.devices()[:1])
    losses = _losses(tr, tok, lab, n=8)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] - 0.05


def test_moe_ep2_matches_dense():
    cfg = _cfg_nodrop()
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                        devices=jax.devices()[:1]), tok, lab)
    got = _losses(HybridParallelTrainer(cfg, MeshConfig(ep=2), seed=3,
                                        devices=jax.devices()[:2]), tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4)


@pytest.mark.slow      # deep-combo compile cost; tier-1 keeps a cheap representative
def test_moe_dp2_ep2_mp2_matches_dense():
    cfg = _cfg_nodrop()
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                        devices=jax.devices()[:1]), tok, lab)
    got = _losses(HybridParallelTrainer(cfg, MeshConfig(dp=2, ep=2, mp=2),
                                        seed=3, devices=jax.devices()[:8]),
                  tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4)


@pytest.mark.slow      # deep-combo compile cost; tier-1 keeps a cheap representative
def test_moe_pp2_ep2_matches_dense():
    cfg = _cfg_nodrop()
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                        devices=jax.devices()[:1]), tok, lab)
    got = _losses(
        HybridParallelTrainer(cfg, MeshConfig(pp=2, ep=2, micro_batches=2),
                              seed=3, devices=jax.devices()[:4]), tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4)


@pytest.mark.slow      # deep-combo compile cost; tier-1 keeps a cheap representative
def test_moe_full_hybrid_dp_pp_ep_zero2_remat():
    cfg = _cfg_nodrop()
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                        devices=jax.devices()[:1]), tok, lab)
    got = _losses(
        HybridParallelTrainer(
            cfg, MeshConfig(dp=2, pp=2, ep=2, micro_batches=2,
                            sharding_stage=2, remat=True),
            seed=3, devices=jax.devices()[:8]), tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4)


def test_moe_aux_loss_trains():
    cfg = gpt_moe_tiny(64, num_experts=4, capacity_factor=2.0)
    assert cfg.moe_aux_weight > 0
    tok, lab = _data(cfg)
    tr = HybridParallelTrainer(cfg, MeshConfig(ep=2, mp=2), seed=3,
                               devices=jax.devices()[:4])
    losses = _losses(tr, tok, lab, n=6)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_expert_params_sharded_over_ep():
    cfg = _cfg_nodrop()
    tr = HybridParallelTrainer(cfg, MeshConfig(ep=4), seed=0,
                               devices=jax.devices()[:4])
    w = tr.params["blocks"]["exp_fc1_w"]  # [L, E, D, F]
    shard_shape = w.sharding.shard_shape(w.shape)
    assert shard_shape[1] == w.shape[1] // 4  # E dim split over ep
    # gate stays replicated
    g = tr.params["blocks"]["gate_w"]
    assert g.sharding.shard_shape(g.shape) == g.shape


def test_capacity_slots_and_drop():
    from paddle_tpu.incubate.distributed.models.moe.dispatch import (
        capacity_slots, combine, dispatch)
    gate_idx = jnp.asarray([[0], [0], [0], [1]], jnp.int32)  # 3 tokens -> e0
    slot, keep = capacity_slots(gate_idx, num_experts=2, capacity=2)
    # first two expert-0 tokens kept, third dropped
    np.testing.assert_array_equal(np.asarray(keep[:, 0]),
                                  [True, True, False, True])
    x = jnp.arange(8, dtype=jnp.float32).reshape(4, 2)
    buf = dispatch(x, slot, 2, 2)
    np.testing.assert_allclose(np.asarray(buf[0, 0]), np.asarray(x[0]))
    np.testing.assert_allclose(np.asarray(buf[0, 1]), np.asarray(x[1]))
    np.testing.assert_allclose(np.asarray(buf[1, 0]), np.asarray(x[3]))
    # combine: identity experts => kept tokens round-trip, dropped -> 0
    val = jnp.ones((4, 1), jnp.float32)
    out = combine(buf, slot, keep, val)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(x[0]))
    np.testing.assert_allclose(np.asarray(out[2]), np.zeros(2))


def test_dispatch_matches_reference_dense_formulation():
    """New slot-scatter dispatch == the GShard one-hot einsum it replaced."""
    rng = np.random.RandomState(0)
    T, D, E, C, k = 32, 8, 4, 16, 2
    x = jnp.asarray(rng.randn(T, D), jnp.float32)
    logits = jnp.asarray(rng.randn(T, E), jnp.float32)
    from paddle_tpu.incubate.distributed.models.moe.dispatch import (
        capacity_slots, combine, dispatch, topk_gating)
    gate_idx, gate_val, _ = topk_gating(logits, k)

    # reference formulation (dense [T,k,E,C] combine tensor)
    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)
    pos = jnp.cumsum(onehot.reshape(T * k, E), axis=0).reshape(T, k, E) * onehot - 1.0
    keep_ref = (pos < C) & (onehot > 0)
    posc = jnp.clip(pos, 0, C - 1).astype(jnp.int32)
    capslot = jax.nn.one_hot(posc, C, dtype=jnp.float32) * keep_ref[..., None]
    comb_ref = jnp.einsum("tk,tkec->tec", gate_val, capslot)
    disp_ref = (comb_ref > 0).astype(x.dtype)
    ein_ref = jnp.einsum("tec,td->ecd", disp_ref, x)

    slot, keep = capacity_slots(gate_idx, E, C)
    ein_new = dispatch(x, slot, E, C)
    np.testing.assert_allclose(np.asarray(ein_new), np.asarray(ein_ref),
                               atol=1e-6)
    eo = ein_new * 2.0  # fake expert output
    out_ref = jnp.einsum("tec,ecd->td", comb_ref, eo)
    out_new = combine(eo, slot, keep, gate_val)
    np.testing.assert_allclose(np.asarray(out_new), np.asarray(out_ref),
                               atol=1e-5)


@pytest.mark.slow      # deep-combo compile cost; tier-1 keeps a cheap representative
def test_moe_interleaved_pp_ep_matches_dense():
    """vpp x pp x ep: expert axis lands on dim 3 after the vpp chunk reshape."""
    from paddle_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
                    max_seq_len=64, moe_num_experts=4, moe_capacity_factor=8.0,
                    moe_aux_weight=0.0)
    tok, lab = _data(cfg)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                        devices=jax.devices()[:1]), tok, lab)
    got = _losses(
        HybridParallelTrainer(cfg, MeshConfig(pp=2, ep=2, vpp=2,
                                              micro_batches=2),
                              seed=3, devices=jax.devices()[:4]), tok, lab)
    np.testing.assert_allclose(got, ref, rtol=1e-4)


@pytest.mark.slow      # deep-combo compile cost; tier-1 keeps a cheap representative
def test_moe_with_cp_and_pp_matches_dense():
    """MoE (dense dispatch per cp shard) under cp x pp: parity incl. the
    aux-loss scale (psum over cp averaged back)."""
    from paddle_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
                    max_seq_len=128, moe_num_experts=4, moe_capacity_factor=8.0)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (4, 128)).astype(np.int32)
    lab = np.roll(tok, -1, 1).astype(np.int32)
    ref = _losses(HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                        devices=jax.devices()[:1]), tok, lab)
    got = _losses(
        HybridParallelTrainer(cfg, MeshConfig(pp=2, cp=2, micro_batches=2),
                              seed=3, devices=jax.devices()[:4]), tok, lab)
    # aux statistics differ slightly per cp shard vs global; loose tolerance
    np.testing.assert_allclose(got, ref, rtol=2e-3)
