"""Continuous-batching serving engine: paged KV cache, slot-indexed decode,
bucketed prefill, scheduler (ref vLLM PagedAttention SOSP 2023 + Orca OSDI
2022; reference repo counterpart: fluid/inference predictor + PaddleNLP
generation)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import gpt as G
from paddle_tpu.inference.cache import PagedKVCache
from paddle_tpu.inference.engine import LLMEngine
from paddle_tpu.incubate.kernels.paged_attention import (
    paged_attention_pallas, paged_attention_xla)


PRESETS = [G.gpt_tiny, G.llama_tiny]
IDS = ["gpt", "llama"]


@pytest.mark.parametrize("preset", PRESETS, ids=IDS)
def test_prefill_decode_logits_match_dense_forward(preset):
    """Per-position logits from prefill + chained decode_step equal the dense
    forward pass (the KV-cache path computes the same function)."""
    cfg = preset(64)
    params = G.init_params(cfg, jax.random.key(0))
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 12)), jnp.int32)
    Tp = 5
    dense = G.forward(params, toks, cfg)            # [B, 12, V]

    kv = G.init_cache(cfg, 2, 12)
    logits, kv = G.prefill(params, toks[:, :Tp], cfg, kv)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(dense[:, Tp - 1]),
                               atol=2e-4, rtol=2e-4)
    for pos in range(Tp, 12):
        logits, kv = G.decode_step(params, toks[:, pos], kv, pos, cfg)
        if pos < 11:
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(dense[:, pos]),
                                       atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("preset", PRESETS, ids=IDS)
def test_paged_decode_logits_match_dense_forward(preset):
    """prefill_paged + chained decode_step_paged reproduce dense-forward
    logits through the page-table indirection (bucket-padded prompt, slots in
    arbitrary page order)."""
    cfg = preset(64)
    params = G.init_params(cfg, jax.random.key(1))
    rng = np.random.RandomState(1)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 12)), jnp.int32)
    dense = G.forward(params, toks, cfg)
    page, Tp, bucket = 4, 5, 8

    pool = G.init_paged_cache(cfg, num_pages=6, page_size=page)
    table = np.zeros((1, 4), np.int32)
    table[0, :4] = [3, 1, 4, 2]                     # deliberately non-contiguous
    ids = np.zeros((1, bucket), np.int32)
    ids[0, :Tp] = np.asarray(toks[0, :Tp])
    logits, pool = G.prefill_paged(params, jnp.asarray(ids), cfg, pool,
                                   jnp.asarray(table[:, :bucket // page]),
                                   jnp.asarray([Tp], jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(dense[:, Tp - 1]),
                               atol=2e-4, rtol=2e-4)
    tbl = jnp.asarray(table)
    for pos in range(Tp, 12):
        logits, pool = G.decode_step_paged(
            params, toks[:, pos], pool, tbl, jnp.asarray([pos], jnp.int32), cfg)
        if pos < 11:
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(dense[:, pos]),
                                       atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("preset", PRESETS, ids=IDS)
def test_engine_matches_generate(preset):
    """End-to-end greedy parity: the continuous-batching engine emits exactly
    the tokens of the one-shot `generate` for mixed-length prompts."""
    cfg = preset(64)
    params = G.init_params(cfg, jax.random.key(0))
    eng = LLMEngine(params, cfg, num_slots=3, page_size=8, max_model_len=64)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 9, 17, 3, 30)]
    rids = [eng.add_request(p, max_new_tokens=6) for p in prompts]
    outs = eng.run()
    for rid, p in zip(rids, prompts):
        ref = G.generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=6)
        np.testing.assert_array_equal(outs[rid].tokens, np.asarray(ref[0]))
        assert outs[rid].finish_reason == "length"


def test_engine_eos_stop_matches_generate_freeze():
    """A request that emits EOS retires with finish_reason='stop' and its
    tokens equal generate()'s output up to the first EOS (generate then
    freezes the tail at EOS; the engine frees the slot instead)."""
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    prompt = np.zeros((3,), np.int32)
    ref = np.asarray(G.generate(params, jnp.asarray(prompt)[None], cfg,
                                max_new_tokens=8)[0])
    eos = int(ref[5])                   # whatever greedy emits at step 5
    frozen = np.asarray(G.generate(params, jnp.asarray(prompt)[None], cfg,
                                   max_new_tokens=8, eos_token_id=eos)[0])
    assert (frozen[6:] == eos).all()    # generate freezes after first EOS

    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    eos_token_id=eos)
    rid = eng.add_request(prompt, max_new_tokens=8)
    out = eng.run()[rid]
    assert out.finish_reason == "stop"
    assert out.token_ids[-1] == eos
    np.testing.assert_array_equal(out.tokens, frozen[:len(out.tokens)])


def test_engine_executable_bound_32_mixed_requests():
    """Acceptance bar: >= 32 mixed-length requests complete with exactly ONE
    decode executable and <= #buckets + 1 prefill executables, on a page pool
    smaller than the dense num_slots * max_model_len footprint."""
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    eng = LLMEngine(params, cfg, num_slots=4, page_size=8, max_model_len=64)
    rng = np.random.RandomState(7)
    n = 32
    rids = []
    for i in range(n):
        lp = int(rng.randint(1, 41))
        prompt = rng.randint(0, cfg.vocab_size, (lp,)).astype(np.int32)
        rids.append(eng.add_request(prompt, max_new_tokens=int(rng.randint(1, 8))))
    outs = eng.run()
    assert sorted(outs) == sorted(rids)                 # every request finished
    st = eng.stats()
    assert st["decode_executables"] == 1
    assert st["prefill_executables"] <= len(eng.buckets) + 1
    # paged memory claim: pool capacity < dense B x max_len footprint
    assert st["kv_token_capacity"] < st["dense_token_footprint"]
    assert st["pages_in_use"] == 0                      # all pages recycled


def test_engine_queues_when_out_of_pages():
    """Admission is reservation-based: with a pool too small for all requests
    at once, later requests wait for pages and still complete."""
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    # 5 real pages of 8 tokens: one 24-token footprint (3 pages) at a time +
    # change, while 4 slots compete
    eng = LLMEngine(params, cfg, num_slots=4, page_size=8, num_pages=6,
                    max_model_len=64)
    prompts = [np.full((16,), i, np.int32) for i in range(6)]
    rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
    outs = eng.run()
    assert sorted(outs) == sorted(rids)
    for rid, p in zip(rids, prompts):
        ref = G.generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=8)
        np.testing.assert_array_equal(outs[rid].tokens, np.asarray(ref[0]))


def test_engine_rejects_impossible_footprint():
    """A request that can never fit the pool is rejected AT INTAKE
    (finish_reason="rejected") instead of raising mid-run or wedging the
    queue head forever — the fail-fast side of the overload work (see
    tests/test_overload.py for the not-wedged proof)."""
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=3,
                    max_model_len=64)      # 2 real pages = 16 tokens capacity
    rid = eng.add_request(np.zeros((20,), np.int32), max_new_tokens=8)
    assert not eng.has_work                # never queued
    assert eng.run()[rid].finish_reason == "rejected"
    assert eng.stats()["rejected_requests"] == 1


def test_engine_non_pow2_max_model_len_served_to_capacity():
    """Buckets cover max_model_len even when it is not a power of 2: a prompt
    longer than the largest power-of-2 bucket still admits and finishes."""
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    eng = LLMEngine(params, cfg, num_slots=2, page_size=16, max_model_len=48)
    assert eng.buckets[-1] == 48
    prompt = np.arange(40, dtype=np.int32) % cfg.vocab_size
    rid = eng.add_request(prompt, max_new_tokens=8)
    out = eng.run()[rid]
    ref = G.generate(params, jnp.asarray(prompt)[None], cfg, max_new_tokens=8)
    np.testing.assert_array_equal(out.tokens, np.asarray(ref[0]))


def test_paged_cache_manager_accounting():
    mgr = PagedKVCache(num_pages=8, page_size=4, num_slots=3,
                       max_pages_per_slot=4)
    assert mgr.num_free_pages == 7                  # page 0 reserved (null)
    assert mgr.token_capacity() == 28
    row = mgr.allocate(0, total_tokens=9)           # ceil(9/4) = 3 pages
    assert (row[:3] > 0).all() and (row[3:] == 0).all()
    assert mgr.pages_in_use() == 3 and mgr.num_free_pages == 4
    with pytest.raises(RuntimeError, match="already has pages"):
        mgr.allocate(0, 4)
    assert not mgr.can_allocate(17)                 # 5 pages > slot max of 4
    assert not mgr.can_allocate(5 * 4)              # and > free pages
    mgr.allocate(1, 16)
    assert mgr.num_free_pages == 0
    with pytest.raises(RuntimeError, match="out of KV pages"):
        mgr.allocate(2, 1)
    mgr.release(0)
    assert mgr.num_free_pages == 3 and (mgr.page_table[0] == 0).all()
    assert mgr.lengths[0] == 0


@pytest.mark.parametrize("kvh", [2, 1], ids=["gqa", "mqa"])
def test_paged_attention_pallas_matches_xla_oracle(kvh):
    """The Pallas paged-decode kernel (interpret mode on CPU) agrees with the
    gather-based XLA oracle, including GQA/MQA grouping and length masking."""
    rng = np.random.RandomState(0)
    B, H, hd, page, P, mp = 3, 4, 64, 8, 7, 4
    q = jnp.asarray(rng.randn(B, H, hd), jnp.float32)
    k = jnp.asarray(rng.randn(P, page, kvh, hd), jnp.float32)
    v = jnp.asarray(rng.randn(P, page, kvh, hd), jnp.float32)
    tbl = np.zeros((B, mp), np.int32)
    tbl[0, :2] = [1, 2]
    tbl[1, :3] = [3, 4, 5]
    tbl[2, :1] = [6]
    lengths = jnp.asarray([13, 20, 5], jnp.int32)
    ref = paged_attention_xla(q, k, v, jnp.asarray(tbl), lengths)
    got = paged_attention_pallas(q, k, v, jnp.asarray(tbl), lengths,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_generate_cache_lru_bounded(monkeypatch):
    """Satellite: the generate executable cache is LRU-bounded (it used to
    grow without limit under varied prompt shapes) and exposes a compile
    counter.  The cap is shrunk so overflowing it costs 7 compiles, not 20."""
    cap = 4
    monkeypatch.setattr(G, "GENERATE_CACHE_MAX", cap)
    cfg = G.gpt_tiny(128)
    params = G.init_params(cfg, jax.random.key(0))
    start = G.generate_cache_stats()["compiles"]
    for tp in range(1, cap + 4):                    # more shapes than the cap
        G.generate(params, jnp.zeros((1, tp), jnp.int32), cfg,
                   max_new_tokens=2)
    st = G.generate_cache_stats()
    assert st["size"] <= cap
    assert st["compiles"] >= start + cap + 3
    # a cached (recently used) shape does not recompile
    before = G.generate_cache_stats()["compiles"]
    G.generate(params, jnp.zeros((1, cap + 3), jnp.int32),
               cfg, max_new_tokens=2)
    assert G.generate_cache_stats()["compiles"] == before


def test_eval_loss_jitted_once():
    """Satellite: HybridParallelTrainer.eval_loss compiles once and reuses
    the executable (it used to retrace eagerly on every call)."""
    from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig
    cfg = G.gpt_tiny(64)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (4, 64)).astype(np.int32)
    lab = np.roll(tok, -1, 1).astype(np.int32)
    tr = HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                               devices=jax.devices()[:1])
    a = float(tr.eval_loss(tok, lab))
    b = float(tr.eval_loss(tok, lab))
    assert a == b
    assert tr._eval_fn._cache_size() == 1
    ref = float(G.loss_fn(tr.params, jnp.asarray(tok), jnp.asarray(lab), cfg))
    np.testing.assert_allclose(a, ref, rtol=1e-5)


def test_steady_state_decode_loop_transfer_guard_clean():
    """Satellite (runtime twin of tpu_lint TPL001/TPL005): once every
    executable is warm, the engine's decode loop performs NO implicit
    host<->device transfers — every h2d is an explicit numpy-backed
    `_h2d` placement and every d2h an explicit np.asarray inside a
    sample-sync span.  `jax.transfer_guard("disallow")` turns any
    regression (a bare Python scalar into a dispatch, an implicit mp
    reshard) into an immediate error.  Exercises chunked prefill,
    prefix-hit + COW admission, speculative verify and vanilla decode
    inside the guard."""
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    num_pages=32, prefill_chunk=16, spec_len=3)
    # pool big enough that the donor's cached pages survive (no LRU eviction
    # between donor retirement and the extension's admission)
    rng = np.random.RandomState(0)
    for n in (5, 20):                   # warm chunk/decode/verify paths
        eng.add_request(rng.randint(0, cfg.vocab_size, (n,))
                        .astype(np.int32), max_new_tokens=4)
    eng.run()
    eng.warm_decode()
    eng.warm_spec()
    base = rng.randint(0, cfg.vocab_size, (13,)).astype(np.int32)
    eng.add_request(base, max_new_tokens=1)
    eng.run()                           # donor registers its prompt pages
    rids = [eng.add_request(rng.randint(0, cfg.vocab_size, (n,))
                            .astype(np.int32), max_new_tokens=5)
            for n in (7, 19, 33)]
    # extension of the donor: prefix hit + COW page copy inside the guard
    rids.append(eng.add_request(np.concatenate([base, base[:4]]),
                                max_new_tokens=3))
    with jax.transfer_guard("disallow"):
        outs = eng.run()
    assert sorted(rids) == sorted(o for o in outs
                                  if o >= rids[0])    # all guarded reqs done
    assert eng.stats()["prefix_cached_tokens"] > 0    # the COW lane ran


def test_bench_serve_cpu_smoke():
    """Satellite (CI wiring): the serving bench's CPU smoke completes N
    requests within the compiled-program bound."""
    from bench_serve import run_serve_bench
    stats = run_serve_bench(num_requests=8, num_slots=2, page_size=8,
                            max_model_len=32, max_new_tokens=3)
    assert stats["requests"] == 8
    assert stats["decode_executables"] == 1
    assert stats["prefill_executables"] <= len(stats["buckets"]) + 1
    assert stats["decode_tokens_per_sec_per_chip"] > 0
