"""nn.Layer system + layer numerics (reference: layer tests in `test/legacy_test/`)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def rnd(*shape):
    return np.random.RandomState(7).rand(*shape).astype(np.float32)


def test_layer_registration():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)
            self.register_buffer("counter", paddle.zeros([1]))

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
    sd = net.state_dict()
    assert "counter" in sd
    assert len(sd) == 5
    out = net(paddle.to_tensor(rnd(3, 4)))
    assert out.shape == [3, 2]


def test_state_dict_roundtrip():
    net1 = nn.Linear(4, 3)
    net2 = nn.Linear(4, 3)
    net2.set_state_dict(net1.state_dict())
    x = paddle.to_tensor(rnd(2, 4))
    np.testing.assert_allclose(net1(x).numpy(), net2(x).numpy())


def test_save_load_roundtrip(tmp_path):
    net = nn.Linear(4, 3)
    path = str(tmp_path / "model.pdparams")
    paddle.save(net.state_dict(), path)
    loaded = paddle.load(path)
    net2 = nn.Linear(4, 3)
    net2.set_state_dict(loaded)
    x = paddle.to_tensor(rnd(2, 4))
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy())


def test_softmax_cross_entropy():
    logits = rnd(4, 5) * 4
    labels = np.array([0, 2, 1, 4], np.int64)
    loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
    # numpy reference
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    expect = -np.log(p[np.arange(4), labels]).mean()
    np.testing.assert_allclose(loss.numpy(), expect, rtol=1e-5)


def test_conv2d_matches_naive():
    x = rnd(1, 2, 5, 5)
    w = rnd(3, 2, 3, 3)
    out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), padding=1)
    assert out.shape == [1, 3, 5, 5]
    # spot check one output position against direct correlation
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    expect = sum((xp[0, c, 1:4, 1:4] * w[0, c]).sum() for c in range(2))
    np.testing.assert_allclose(out.numpy()[0, 0, 1, 1], expect, rtol=1e-4)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.to_tensor(rnd(4, 3, 2, 2) * 5)
    bn.train()
    y = bn(x)
    m = y.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, np.zeros(3), atol=1e-4)
    # running stats moved
    assert not np.allclose(bn._mean.numpy(), np.zeros(3))
    bn.eval()
    y2 = bn(x)
    assert y2.shape == [4, 3, 2, 2]


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.to_tensor(rnd(2, 4, 8) * 3)
    y = ln(x).numpy()
    np.testing.assert_allclose(y.mean(-1), np.zeros((2, 4)), atol=1e-5)
    np.testing.assert_allclose(y.std(-1), np.ones((2, 4)), atol=1e-2)


def test_embedding():
    emb = nn.Embedding(10, 4)
    ids = paddle.to_tensor(np.array([[1, 2], [3, 9]], np.int64))
    out = emb(ids)
    assert out.shape == [2, 2, 4]
    np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1])
    out.sum().backward()
    assert emb.weight.grad is not None


def test_pooling():
    x = paddle.to_tensor(rnd(1, 1, 4, 4))
    out = F.max_pool2d(x, 2)
    expect = x.numpy().reshape(1, 1, 2, 2, 2, 2).max((3, 5))
    np.testing.assert_allclose(out.numpy(), expect)
    out2 = F.avg_pool2d(x, 2)
    expect2 = x.numpy().reshape(1, 1, 2, 2, 2, 2).mean((3, 5))
    np.testing.assert_allclose(out2.numpy(), expect2, rtol=1e-6)


def test_adaptive_pool():
    x = paddle.to_tensor(rnd(1, 2, 6, 6))
    out = F.adaptive_avg_pool2d(x, 2)
    assert out.shape == [1, 2, 2, 2]


def test_dropout_modes():
    x = paddle.to_tensor(np.ones((100, 100), np.float32))
    y = F.dropout(x, 0.5, training=True)
    kept = (y.numpy() != 0).mean()
    assert 0.4 < kept < 0.6
    # upscale keeps expectation
    np.testing.assert_allclose(y.numpy().mean(), 1.0, atol=0.05)
    y_eval = F.dropout(x, 0.5, training=False)
    np.testing.assert_allclose(y_eval.numpy(), x.numpy())


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.to_tensor(rnd(2, 5, 16))
    out = mha(x, x, x)
    assert out.shape == [2, 5, 16]
    out.sum().backward()
    assert mha.q_proj.weight.grad is not None


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.to_tensor(rnd(2, 5, 16))
    out = enc(x)
    assert out.shape == [2, 5, 16]


def test_lstm():
    lstm = nn.LSTM(4, 8, num_layers=1)
    x = paddle.to_tensor(rnd(2, 3, 4))
    out, (h, c) = lstm(x)
    assert out.shape == [2, 3, 8]
    assert h.shape == [1, 2, 8]
    out.sum().backward()


def test_sequential_containers():
    seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    assert len(seq) == 3
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(list(ll.parameters())) == 8


def test_initializers_seeded():
    paddle.seed(123)
    l1 = nn.Linear(16, 16)
    paddle.seed(123)
    l2 = nn.Linear(16, 16)
    np.testing.assert_allclose(l1.weight.numpy(), l2.weight.numpy())


def test_clip_grad_by_global_norm():
    from paddle_tpu.nn import ClipGradByGlobalNorm
    p = paddle.to_tensor(rnd(3, 3), stop_gradient=False)
    g = paddle.to_tensor(np.full((3, 3), 10.0, np.float32))
    clip = ClipGradByGlobalNorm(1.0)
    out = clip([(p, g)])
    norm = np.linalg.norm(out[0][1].numpy())
    np.testing.assert_allclose(norm, 1.0, rtol=1e-5)
