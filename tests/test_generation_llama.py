"""KV-cache generation + Llama preset + DP gradient bucketing
(ref PaddleNLP generation; EagerReducer bucket fusion, reducer.cc:1068)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import gpt as G


@pytest.mark.parametrize("preset", [G.gpt_tiny, G.llama_tiny],
                         ids=["gpt", "llama"])
def test_greedy_generate_matches_full_forward(preset):
    cfg = preset(64)
    params = G.init_params(cfg, jax.random.key(0))
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 5)), jnp.int32)
    out = G.generate(params, prompt, cfg, max_new_tokens=6)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))
    logits = G.forward(params, out, cfg)
    for t in range(4, 10):
        np.testing.assert_array_equal(np.asarray(out[:, t + 1]),
                                      np.asarray(jnp.argmax(logits[:, t], -1)))


def test_generate_layer_api_and_sampling():
    model = G.GPTForCausalLM(G.gpt_tiny(64))
    prompt = paddle.to_tensor(np.zeros((1, 3), np.int32))
    out = model.generate(prompt, max_new_tokens=5)
    assert out.shape == [1, 8]
    s = model.generate(prompt, max_new_tokens=5, temperature=0.9, top_k=8)
    assert s.shape == [1, 8] and (np.asarray(s._data) < 256).all()


def test_llama_trains_in_hybrid_trainer():
    from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig
    cfg = G.llama_tiny(64)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, cfg.vocab_size, (8, 64)).astype(np.int32)
    lab = np.roll(tok, -1, 1).astype(np.int32)
    ref = [float(HybridParallelTrainer(cfg, MeshConfig(), seed=3,
                                       devices=jax.devices()[:1])
                 .train_step(tok, lab))]
    tr = HybridParallelTrainer(cfg, MeshConfig(dp=2, mp=2), seed=3,
                               devices=jax.devices()[:4])
    got = [float(tr.train_step(tok, lab))]
    np.testing.assert_allclose(got, ref, rtol=1e-4)


def test_hybrid_convergence_long_horizon():
    """VERDICT weak #6: longer-horizon hybrid training stays on the
    single-chip loss curve (20 steps, dp2 x mp2 + ZeRO-2 + remat)."""
    from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig
    cfg = G.gpt_tiny(64)
    rng = np.random.RandomState(1)
    tok = rng.randint(0, cfg.vocab_size, (8, 64)).astype(np.int32)
    lab = np.roll(tok, -1, 1).astype(np.int32)
    single = HybridParallelTrainer(cfg, MeshConfig(), seed=5,
                                   devices=jax.devices()[:1])
    hybrid = HybridParallelTrainer(
        cfg, MeshConfig(dp=2, mp=2, sharding_stage=2, remat=True), seed=5,
        devices=jax.devices()[:4])
    ls = [float(single.train_step(tok, lab)) for _ in range(20)]
    lh = [float(hybrid.train_step(tok, lab)) for _ in range(20)]
    np.testing.assert_allclose(lh, ls, rtol=5e-4)
    assert ls[-1] < ls[0] - 0.25  # actually converging, not flat


def test_dp_bucketing_single_process_passthrough():
    """world=1: DataParallel hooks are inert and grads are untouched."""
    import paddle_tpu.nn as nn
    model = paddle.DataParallel(nn.Linear(4, 2))
    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    loss = (model(x) ** 2).sum()
    loss.backward()
    g = model._layers.weight.grad
    assert g is not None and np.isfinite(g.numpy()).all()


def test_generate_honors_eos():
    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    prompt = jnp.zeros((1, 3), jnp.int32)
    ref = G.generate(params, prompt, cfg, max_new_tokens=8)
    eos = int(np.asarray(ref[0, 5]))  # whatever greedy emits at step 5
    out = G.generate(params, prompt, cfg, max_new_tokens=8, eos_token_id=eos)
    tail = np.asarray(out[0, 6:])
    assert (tail == eos).all()  # frozen at EOS after first emission


def test_generate_seq_len_bound():
    cfg = G.gpt_tiny(16)
    cfg.use_rope = False
    params = G.init_params(cfg, jax.random.key(0))
    with pytest.raises(ValueError, match="max_seq_len"):
        G.generate(params, jnp.zeros((1, 10), jnp.int32), cfg,
                   max_new_tokens=10)


def test_dp_bucketing_shared_param_and_flush_callback():
    """Shared params fire one hook per consumer edge; the engine-completion
    flush must still produce correct (single-process: unchanged) grads."""
    import paddle_tpu.nn as nn

    class Tied(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)

        def forward(self, x):
            return self.lin(self.lin(x))   # weight used twice

    ref = Tied()
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = (ref(x) ** 2).sum()
    loss.backward()
    expected = ref.lin.weight.grad.numpy()

    model = Tied()
    model.set_state_dict(ref.state_dict())
    dp = paddle.DataParallel(model)
    loss2 = (dp(x) ** 2).sum()
    loss2.backward()
    np.testing.assert_allclose(model.lin.weight.grad.numpy(), expected,
                               rtol=1e-5)
