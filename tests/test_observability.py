"""Serving observability: metrics registry (Counter/Gauge/Histogram +
Prometheus/JSON export), request lifecycle latency tracking, and engine step
tracing (ref `python/paddle/profiler/profiler.py` + `fluid/platform/profiler/`
span tree / chrome export; Orca OSDI'22 + vLLM SOSP'23 serving metrics)."""
import json

import numpy as np
import pytest

import jax

from paddle_tpu.models import gpt as G
from paddle_tpu.inference.engine import ENGINE_SPANS, LLMEngine
from paddle_tpu.inference.metrics import (Counter, Gauge, Histogram,
                                          MetricsRegistry, log_buckets)
from paddle_tpu.inference.spec import NgramProposer


# ---------------------------------------------------------------------------
# metrics primitives (pure host, no jax)
# ---------------------------------------------------------------------------

def test_log_buckets_geometric_cover():
    edges = log_buckets(0.001, 1.0, per_decade=3)
    assert edges[0] == pytest.approx(0.001)
    assert edges[-1] >= 1.0
    ratios = [b / a for a, b in zip(edges, edges[1:])]
    assert all(r == pytest.approx(10 ** (1 / 3)) for r in ratios)
    with pytest.raises(ValueError):
        log_buckets(0.0, 1.0)
    with pytest.raises(ValueError):
        log_buckets(1.0, 0.5)


def test_histogram_bucket_edges_le_semantics():
    """A value exactly on an edge lands in that edge's bucket (le semantics);
    past the last edge it lands in overflow but count/sum/max stay exact."""
    h = Histogram("x", buckets=[1.0, 2.0, 4.0, 8.0])
    for v in (1.0, 1.5, 2.0, 2.0001, 9.0):
        h.observe(v)
    assert h.counts == [1, 2, 1, 0]
    assert h.overflow == 1
    assert h.count == 5
    assert h.sum == pytest.approx(1.0 + 1.5 + 2.0 + 2.0001 + 9.0)
    assert h.min == 1.0 and h.max == 9.0


def test_histogram_percentile_interpolation_exact():
    """Percentiles interpolate linearly inside the covering bucket — checked
    against hand-computed values, clamped to the observed envelope."""
    h = Histogram("x", buckets=[1.0, 2.0, 4.0])
    for _ in range(5):
        h.observe(1.0)          # bucket (0, 1]
    for _ in range(5):
        h.observe(4.0)          # bucket (2, 4]
    # p50: rank 5 covered by the first bucket -> 0 + 1 * 5/5 = 1.0
    assert h.percentile(50) == pytest.approx(1.0)
    # p90: rank 9 -> second occupied bucket: 2 + (4-2) * (9-5)/5 = 3.6
    assert h.percentile(90) == pytest.approx(3.6)
    # p99: rank 9.9 -> 2 + 2 * 4.9/5 = 3.96
    assert h.percentile(99) == pytest.approx(3.96)
    assert h.percentile(0) == 1.0           # envelope, not bucket edge
    assert h.percentile(100) == 4.0
    assert h.percentile(50) <= h.percentile(90) <= h.percentile(99)
    with pytest.raises(ValueError):
        h.percentile(101)


def test_histogram_overflow_and_clamp():
    h = Histogram("x", buckets=[1.0, 2.0])
    h.observe(100.0)            # overflow bucket
    h.observe(1.5)
    assert h.percentile(99) == 100.0        # overflow reports observed max
    # a lone observation in a wide bucket must not interpolate below itself
    g = Histogram("y", buckets=[0.001, 100.0])
    g.observe(50.0)
    assert g.percentile(1) == 50.0
    assert g.percentile(99) == 50.0
    empty = Histogram("z", buckets=[1.0])
    assert empty.percentile(50) == 0.0 and empty.min == 0.0


def test_counter_monotone_and_registry_dedup():
    reg = MetricsRegistry(namespace="t")
    c = reg.counter("events")
    c.inc()
    c.inc(3)
    assert c.value == 4
    with pytest.raises(ValueError):
        c.inc(-1)
    assert reg.counter("events") is c       # idempotent factory
    with pytest.raises(TypeError):
        reg.gauge("events")                 # name/type conflict
    g = reg.gauge("level", lambda: 7)
    assert g.value == 7
    with pytest.raises(ValueError):
        g.set(3.0)                          # callback gauges are read-only
    h = reg.histogram("lat", buckets=[1.0, 2.0])
    h.observe(1.5)
    reg.reset()
    assert c.value == 0 and h.count == 0
    assert g.value == 7                     # callback gauges read live state


def test_registry_clock_injection_and_snapshot_json():
    t = [41.5]
    reg = MetricsRegistry(clock=lambda: t[0])
    assert reg.now() == 41.5
    t[0] = 43.25
    assert reg.now() == 43.25
    reg.counter("c").inc(2)
    reg.histogram("h", buckets=[1.0]).observe(0.5)
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["counters"]["c"] == 2
    assert snap["histograms"]["h"]["count"] == 1


def test_prometheus_exposition_parses():
    """The text exposition validates under the same checker CI runs
    (tools/check_metrics.py): well-formed lines, cumulative buckets ending
    at +Inf == _count, sum/count samples present."""
    from tools.check_metrics import check_exposition, parse_prometheus
    reg = MetricsRegistry(namespace="llm_engine")
    reg.counter("decode_tokens", "tokens").inc(7)
    reg.gauge("queued", lambda: 3, "depth")
    h = reg.histogram("ttft_seconds", buckets=[0.1, 1.0, 10.0], help="ttft")
    for v in (0.05, 0.5, 0.5, 20.0):
        h.observe(v)
    text = reg.to_prometheus()
    errors = []
    check_exposition(text, errors)
    assert not errors, errors
    samples = parse_prometheus(text)
    assert samples["llm_engine_decode_tokens_total"][0][1] == 7
    assert samples["llm_engine_queued"][0][1] == 3
    buckets = dict(samples["llm_engine_ttft_seconds_bucket"])
    assert buckets['{le="0.1"}'] == 1       # cumulative
    assert buckets['{le="1"}'] == 3
    assert buckets['{le="10"}'] == 3
    assert buckets['{le="+Inf"}'] == 4
    assert samples["llm_engine_ttft_seconds_count"][0][1] == 4


def test_ngram_proposer_telemetry():
    p = NgramProposer(max_ngram=2)
    ctx = np.array([5, 6, 7, 5, 6], np.int32)
    assert p.propose(ctx, 2) is not None    # trailing (5,6) recurs
    assert p.propose(np.arange(8, dtype=np.int32), 2) is None
    st = p.stats()
    assert st["propose_calls"] == 2 and st["propose_hits"] == 1
    assert st["tokens_proposed"] >= 1 and st["hit_rate"] == 0.5
    p.reset_stats()
    assert p.stats()["propose_calls"] == 0


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    cfg = G.gpt_tiny(64)
    return cfg, G.init_params(cfg, jax.random.key(0))


@pytest.fixture(scope="module")
def spec_eng(tiny):
    """Shared chunked + speculative engine with a pool small enough to force
    LRU eviction — counters only ever grow across the tests that share it."""
    cfg, params = tiny
    return LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=9,
                     max_model_len=64, prefill_chunk=16, spec_len=3, seed=3)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_request_lifecycle_fake_clock(tiny):
    """Deterministic lifecycle math through the injectable clock: queue time,
    TTFT, TPOT and e2e land exactly where the clock was set, in both the
    per-request record and the engine histograms."""
    cfg, params = tiny
    clk = FakeClock(10.0)
    # double_buffer=False: this test pins exact per-step stamp math, which
    # needs tokens observed in the step that dispatched them (the deferred-
    # harvest ordering has its own test in test_fused_step.py)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    clock=clk, double_buffer=False)
    rid = eng.add_request(np.arange(5, dtype=np.int32), max_new_tokens=3)
    clk.t = 12.0
    # one step() = admit + bucketed prefill (first token) + a decode
    # iteration (second token), all stamped at t=12
    assert eng.step() == []
    clk.t = 15.5
    outs = eng.step()           # third token -> finish
    assert [o.request_id for o in outs] == [rid]
    m = outs[0].metrics
    assert m.t_enqueue == 10.0 and m.t_admit == 12.0
    assert m.queue_s == pytest.approx(2.0)
    assert m.ttft_s == pytest.approx(2.0) and outs[0].ttft_s == m.ttft_s
    assert m.t_first_token == 12.0 and m.t_finish == 15.5
    assert m.e2e_s == pytest.approx(5.5)
    assert m.tpot_s == pytest.approx((15.5 - 12.0) / 2)
    assert m.n_generated == 3
    lat = eng.stats()["latency"]
    assert lat["queue_s"]["count"] == 1
    assert lat["queue_s"]["sum"] == pytest.approx(2.0)
    assert lat["ttft_s"]["max"] == pytest.approx(2.0)
    assert lat["e2e_s"]["sum"] == pytest.approx(5.5)
    assert lat["tpot_s"]["mean"] == pytest.approx(1.75)


def test_lifecycle_covers_abort_and_prefix_hit(tiny):
    """The abort path closes the record (with its own counter, not the
    latency histograms); a prefix-hit admission carries cached_tokens into
    the record."""
    cfg, params = tiny
    clk = FakeClock(100.0)
    eng = LLMEngine(params, cfg, num_slots=1, page_size=8, num_pages=17,
                    max_model_len=64, prefill_chunk=8, clock=clk)
    prompt = (np.arange(20, dtype=np.int32) * 7 + 1) % cfg.vocab_size
    rid = eng.add_request(prompt, max_new_tokens=4)
    eng.run()
    # same prompt again: admission maps the cached prefix
    rid2 = eng.add_request(prompt, max_new_tokens=4)
    eng.step()
    out2 = eng.run()[rid2]
    assert out2.metrics.cached_tokens > 0
    assert out2.cached_tokens == out2.metrics.cached_tokens
    # queued abort: never admitted -> no admission stamp, reason recorded
    blocker = eng.add_request(prompt[:9], max_new_tokens=40)
    clk.t = 101.0
    waiting = eng.add_request(prompt[:5], max_new_tokens=4)
    eng.step()
    e2e_before = eng.stats()["latency"]["e2e_s"]["count"]
    clk.t = 103.0
    assert eng.abort(waiting)           # still queued: slot held by blocker
    assert eng.abort(blocker)           # running
    out = eng.run()[waiting]
    assert out.finish_reason == "abort"
    assert out.metrics.t_admit is None and out.metrics.queue_s is None
    assert out.metrics.e2e_s == pytest.approx(2.0)
    st = eng.stats()
    assert st["aborted_requests"] == 2
    assert st["latency"]["e2e_s"]["count"] == e2e_before  # aborts excluded


def test_counters_monotonic_across_abort_and_eviction(spec_eng):
    """No counter ever decreases while the engine churns through prefix
    hits, LRU eviction and a mid-flight abort; the page partition stays
    consistent afterwards."""
    eng = spec_eng
    rng = np.random.RandomState(5)
    shared = rng.randint(0, eng.config.vocab_size, (20,)).astype(np.int32)
    rids = []
    for i in range(8):
        if i % 3 == 0:
            tail = rng.randint(0, eng.config.vocab_size, (i,)).astype(np.int32)
            prompt = np.concatenate([shared, tail]) if i else shared.copy()
        else:
            prompt = rng.randint(0, eng.config.vocab_size,
                                 (int(rng.randint(4, 40)),)).astype(np.int32)
        rids.append(eng.add_request(prompt, max_new_tokens=6))
    prev = eng.metrics.snapshot()["counters"]
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        if steps == 3:
            assert eng.abort(rids[-1])
        cur = eng.metrics.snapshot()["counters"]
        for k, v in cur.items():
            assert v >= prev[k], f"counter {k} decreased: {prev[k]} -> {v}"
        prev = cur
    st = eng.stats()
    assert st["aborted_requests"] >= 1
    assert st["prefix_evictions"] >= 1          # pool pressure hit the LRU
    assert st["prefix_evictions"] == prev["prefix_evictions"]  # mirror synced
    assert st["spec_events"] > 0
    eng.cache.check_invariants()


def test_stats_spec_events_recompute_acceptance(spec_eng):
    """Satellite: spec_events is reported, so accepted_per_step is
    recomputable from the stats dict alone."""
    st = spec_eng.stats()
    assert st["spec_events"] > 0
    assert st["accepted_per_step"] == pytest.approx(
        st["spec_emitted_tokens"] / st["spec_events"])


def test_chrome_trace_and_step_timeline(spec_eng, tmp_path):
    """engine.trace(dir) exports a valid chrome trace holding the engine's
    host-phase span names, the step-timeline ring, and a metrics snapshot."""
    eng = spec_eng
    td = tmp_path / "trace"
    with eng.trace(str(td), device=False):
        rng = np.random.RandomState(9)
        for n in (5, 18, 30):
            eng.add_request(rng.randint(0, eng.config.vocab_size,
                                        (n,)).astype(np.int32),
                            max_new_tokens=4)
        eng.run()
    host = json.loads((td / "host_trace.json").read_text())
    names = {e["name"] for e in host["traceEvents"]}
    # fused engine (default): the one-dispatch step emits the fused span in
    # place of the legacy verify/decode/chunk dispatch spans
    assert {"engine.step", "engine.admit", "engine.fused.dispatch",
            "engine.spec.propose", "engine.spec.accept",
            "engine.sample.sync"} <= names
    assert names <= set(ENGINE_SPANS)
    for e in host["traceEvents"]:
        assert e["ph"] == "X" and e["dur"] >= 0
    timeline = json.loads((td / "step_timeline.json").read_text())
    assert timeline and timeline[-1]["step"] >= len(timeline)
    for key in ("decode_batch", "chunk", "verify_dispatches",
                "tokens_emitted", "pages_in_use", "pages_free",
                "pages_evictable", "queued", "running", "prefilling",
                "v", "fused", "dispatches", "sync_ms", "slots"):
        assert key in timeline[-1]
    assert any(r["tokens_emitted"] > 0 for r in timeline)
    snap = json.loads((td / "metrics.json").read_text())
    assert snap["counters"]["decode_tokens"] > 0
    assert snap["proposer"]["propose_calls"] > 0
    # spans are recorded only inside a trace window
    n_before = len(eng.step_trace())
    eng.add_request(np.arange(4, dtype=np.int32), max_new_tokens=2)
    eng.run()
    assert len(eng.step_trace()) > n_before


def test_trace_rides_outer_profiler(spec_eng, tmp_path):
    """engine.trace() nested inside a user Profiler must not wipe the outer
    event buffer or stop the outer recording — it rides it and snapshots."""
    from paddle_tpu.profiler import Profiler, RecordEvent, is_recording
    from paddle_tpu.profiler import profiler as prof_mod
    eng = spec_eng
    with Profiler(timer_only=True):
        with RecordEvent("outer.before"):
            pass
        with eng.trace(str(tmp_path / "t"), device=False):
            eng.add_request(np.arange(4, dtype=np.int32), max_new_tokens=2)
            eng.run()
        assert is_recording()           # outer recording still live
        with RecordEvent("outer.after"):
            pass
        names = {e.name for e in prof_mod._events}
        assert {"outer.before", "engine.step", "outer.after"} <= names
    host = json.loads((tmp_path / "t" / "host_trace.json").read_text())
    snap_names = {e["name"] for e in host["traceEvents"]}
    assert "engine.step" in snap_names and "outer.before" in snap_names


def test_step_trace_ring_bounded(tiny):
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                    trace_ring=4)
    eng.add_request(np.arange(3, dtype=np.int32), max_new_tokens=10)
    eng.run()
    trace = eng.step_trace()
    assert len(trace) == 4                      # ring capped
    assert trace[-1]["step"] > 4                # but steps kept counting
    eng.reset_counters()
    assert eng.step_trace() == []
    assert eng.stats()["decode_tokens"] == 0


def test_stats_execs_fallback_attribute_error_only(spec_eng, monkeypatch):
    """Satellite: a missing _cache_size falls back to the tracked count, but
    a REAL failure inside _cache_size propagates instead of being silently
    absorbed into the fallback number."""
    class _NoSize:
        pass

    class _Boom:
        def _cache_size(self):
            raise RuntimeError("bug inside the executable cache")

    monkeypatch.setattr(spec_eng, "_decode_fn", _NoSize())
    st = spec_eng.stats()       # fallback path: tracked approximation
    assert st["decode_executables"] in (0, 1)
    monkeypatch.setattr(spec_eng, "_decode_fn", _Boom())
    with pytest.raises(RuntimeError, match="bug inside"):
        spec_eng.stats()


GOLDEN_STATS_KEYS = frozenset({
    # frozen pre-observability surface (PRs 1-4): benches and tests consume
    # these — removing or renaming any of them is an API break
    "decode_executables", "verify_executables", "prefill_executables",
    "copy_executables", "buckets", "prefill_chunk", "spec_len", "mp",
    "decode_iterations", "decode_tokens", "verify_steps",
    "spec_drafted_tokens", "spec_accepted_tokens", "spec_emitted_tokens",
    "spec_backoffs", "accepted_per_step", "prefill_chunks",
    "prefilled_tokens", "prefix_cached_tokens", "prefix_hit_requests",
    "prefix_hit_rate", "cow_page_copies", "pages_in_use", "pages_free",
    "pages_evictable", "prefix_evictions", "kv_token_capacity",
    "dense_token_footprint", "queued", "prefilling", "running",
})
NEW_STATS_KEYS = frozenset({
    # added by the observability PR
    "engine_steps", "spec_events", "finished_requests", "aborted_requests",
    "latency",
}) | frozenset({
    # added by the oversubscription PR (overload surface)
    "swap_executables", "admission", "preempt", "preemptions",
    "preempt_swaps", "preempt_recomputes", "swapped_pages", "swap_ms",
    "recomputed_tokens", "timeouts", "rejected_requests", "swapped",
    "kv_pages_swapped", "kv_pool_pressure",
}) | frozenset({
    # added by the quantized-serving PR (weight/kv int8 + intake admission)
    "weight_dtype", "kv_dtype", "kv_pool_bytes", "intake_swap_rejects",
})


def test_stats_keyset_backcompat_golden(spec_eng):
    """Every pre-observability stats() key survives byte-for-byte, and the
    full key set is exactly golden + the documented additions — an
    accidental key (or a dropped one) fails here before a bench does."""
    keys = set(spec_eng.stats())
    assert GOLDEN_STATS_KEYS <= keys
    assert keys == GOLDEN_STATS_KEYS | NEW_STATS_KEYS
    lat = spec_eng.stats()["latency"]
    assert set(lat) == {"queue_s", "ttft_s", "tpot_s", "e2e_s", "step_s"}
    for summ in lat.values():
        assert set(summ) == {"count", "sum", "mean", "min", "max",
                             "p50", "p90", "p99"}


def test_check_metrics_tool(tmp_path):
    """Satellite (CI wiring): the metrics schema guard passes on the live
    engine and its parser rejects malformed exposition text."""
    import tools.check_metrics as cm
    errors = []
    eng, st = cm.run_smoke(errors)
    assert not errors, errors
    assert cm.REQUIRED_STATS_KEYS <= set(st)
    check_errors = []
    cm.check_exposition(eng.metrics.to_prometheus(), check_errors)
    assert not check_errors, check_errors
    with pytest.raises(ValueError, match="malformed sample"):
        cm.parse_prometheus("bad metric line {")
    broken = ('m_bucket{le="1"} 5\nm_bucket{le="+Inf"} 3\n'
              'm_sum 1.0\nm_count 3\n')
    errs = []
    cm.check_exposition(broken, errs)
    assert any("cumulative" in e for e in errs)
