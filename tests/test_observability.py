"""Serving observability: metrics registry (Counter/Gauge/Histogram +
Prometheus/JSON export), request lifecycle latency tracking, and engine step
tracing (ref `python/paddle/profiler/profiler.py` + `fluid/platform/profiler/`
span tree / chrome export; Orca OSDI'22 + vLLM SOSP'23 serving metrics)."""
import json

import numpy as np
import pytest

import jax

from paddle_tpu.models import gpt as G
from paddle_tpu.inference.engine import ENGINE_SPANS, LLMEngine
from paddle_tpu.inference.faults import FaultPlan
from paddle_tpu.inference.metrics import (Counter, FleetMetrics, Gauge,
                                          Histogram, MetricsRegistry,
                                          log_buckets)
from paddle_tpu.inference.spec import NgramProposer
from paddle_tpu.inference.tracing import RequestTrace


# ---------------------------------------------------------------------------
# metrics primitives (pure host, no jax)
# ---------------------------------------------------------------------------

def test_log_buckets_geometric_cover():
    edges = log_buckets(0.001, 1.0, per_decade=3)
    assert edges[0] == pytest.approx(0.001)
    assert edges[-1] >= 1.0
    ratios = [b / a for a, b in zip(edges, edges[1:])]
    assert all(r == pytest.approx(10 ** (1 / 3)) for r in ratios)
    with pytest.raises(ValueError):
        log_buckets(0.0, 1.0)
    with pytest.raises(ValueError):
        log_buckets(1.0, 0.5)


def test_histogram_bucket_edges_le_semantics():
    """A value exactly on an edge lands in that edge's bucket (le semantics);
    past the last edge it lands in overflow but count/sum/max stay exact."""
    h = Histogram("x", buckets=[1.0, 2.0, 4.0, 8.0])
    for v in (1.0, 1.5, 2.0, 2.0001, 9.0):
        h.observe(v)
    assert h.counts == [1, 2, 1, 0]
    assert h.overflow == 1
    assert h.count == 5
    assert h.sum == pytest.approx(1.0 + 1.5 + 2.0 + 2.0001 + 9.0)
    assert h.min == 1.0 and h.max == 9.0


def test_histogram_percentile_interpolation_exact():
    """Percentiles interpolate linearly inside the covering bucket — checked
    against hand-computed values, clamped to the observed envelope."""
    h = Histogram("x", buckets=[1.0, 2.0, 4.0])
    for _ in range(5):
        h.observe(1.0)          # bucket (0, 1]
    for _ in range(5):
        h.observe(4.0)          # bucket (2, 4]
    # p50: rank 5 covered by the first bucket -> 0 + 1 * 5/5 = 1.0
    assert h.percentile(50) == pytest.approx(1.0)
    # p90: rank 9 -> second occupied bucket: 2 + (4-2) * (9-5)/5 = 3.6
    assert h.percentile(90) == pytest.approx(3.6)
    # p99: rank 9.9 -> 2 + 2 * 4.9/5 = 3.96
    assert h.percentile(99) == pytest.approx(3.96)
    assert h.percentile(0) == 1.0           # envelope, not bucket edge
    assert h.percentile(100) == 4.0
    assert h.percentile(50) <= h.percentile(90) <= h.percentile(99)
    with pytest.raises(ValueError):
        h.percentile(101)


def test_histogram_overflow_and_clamp():
    h = Histogram("x", buckets=[1.0, 2.0])
    h.observe(100.0)            # overflow bucket
    h.observe(1.5)
    assert h.percentile(99) == 100.0        # overflow reports observed max
    # a lone observation in a wide bucket must not interpolate below itself
    g = Histogram("y", buckets=[0.001, 100.0])
    g.observe(50.0)
    assert g.percentile(1) == 50.0
    assert g.percentile(99) == 50.0
    empty = Histogram("z", buckets=[1.0])
    assert empty.percentile(50) == 0.0 and empty.min == 0.0


def test_counter_monotone_and_registry_dedup():
    reg = MetricsRegistry(namespace="t")
    c = reg.counter("events")
    c.inc()
    c.inc(3)
    assert c.value == 4
    with pytest.raises(ValueError):
        c.inc(-1)
    assert reg.counter("events") is c       # idempotent factory
    with pytest.raises(TypeError):
        reg.gauge("events")                 # name/type conflict
    g = reg.gauge("level", lambda: 7)
    assert g.value == 7
    with pytest.raises(ValueError):
        g.set(3.0)                          # callback gauges are read-only
    h = reg.histogram("lat", buckets=[1.0, 2.0])
    h.observe(1.5)
    reg.reset()
    assert c.value == 0 and h.count == 0
    assert g.value == 7                     # callback gauges read live state


def test_registry_clock_injection_and_snapshot_json():
    t = [41.5]
    reg = MetricsRegistry(clock=lambda: t[0])
    assert reg.now() == 41.5
    t[0] = 43.25
    assert reg.now() == 43.25
    reg.counter("c").inc(2)
    reg.histogram("h", buckets=[1.0]).observe(0.5)
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["counters"]["c"] == 2
    assert snap["histograms"]["h"]["count"] == 1


def test_prometheus_exposition_parses():
    """The text exposition validates under the same checker CI runs
    (tools/check_metrics.py): well-formed lines, cumulative buckets ending
    at +Inf == _count, sum/count samples present."""
    from tools.check_metrics import check_exposition, parse_prometheus
    reg = MetricsRegistry(namespace="llm_engine")
    reg.counter("decode_tokens", "tokens").inc(7)
    reg.gauge("queued", lambda: 3, "depth")
    h = reg.histogram("ttft_seconds", buckets=[0.1, 1.0, 10.0], help="ttft")
    for v in (0.05, 0.5, 0.5, 20.0):
        h.observe(v)
    text = reg.to_prometheus()
    errors = []
    check_exposition(text, errors)
    assert not errors, errors
    samples = parse_prometheus(text)
    assert samples["llm_engine_decode_tokens_total"][0][1] == 7
    assert samples["llm_engine_queued"][0][1] == 3
    buckets = dict(samples["llm_engine_ttft_seconds_bucket"])
    assert buckets['{le="0.1"}'] == 1       # cumulative
    assert buckets['{le="1"}'] == 3
    assert buckets['{le="10"}'] == 3
    assert buckets['{le="+Inf"}'] == 4
    assert samples["llm_engine_ttft_seconds_count"][0][1] == 4


def test_ngram_proposer_telemetry():
    p = NgramProposer(max_ngram=2)
    ctx = np.array([5, 6, 7, 5, 6], np.int32)
    assert p.propose(ctx, 2) is not None    # trailing (5,6) recurs
    assert p.propose(np.arange(8, dtype=np.int32), 2) is None
    st = p.stats()
    assert st["propose_calls"] == 2 and st["propose_hits"] == 1
    assert st["tokens_proposed"] >= 1 and st["hit_rate"] == 0.5
    p.reset_stats()
    assert p.stats()["propose_calls"] == 0


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    cfg = G.gpt_tiny(64)
    return cfg, G.init_params(cfg, jax.random.key(0))


@pytest.fixture(scope="module")
def spec_eng(tiny):
    """Shared chunked + speculative engine with a pool small enough to force
    LRU eviction — counters only ever grow across the tests that share it."""
    cfg, params = tiny
    return LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=9,
                     max_model_len=64, prefill_chunk=16, spec_len=3, seed=3)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_request_lifecycle_fake_clock(tiny):
    """Deterministic lifecycle math through the injectable clock: queue time,
    TTFT, TPOT and e2e land exactly where the clock was set, in both the
    per-request record and the engine histograms."""
    cfg, params = tiny
    clk = FakeClock(10.0)
    # double_buffer=False: this test pins exact per-step stamp math, which
    # needs tokens observed in the step that dispatched them (the deferred-
    # harvest ordering has its own test in test_fused_step.py)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    clock=clk, double_buffer=False)
    rid = eng.add_request(np.arange(5, dtype=np.int32), max_new_tokens=3)
    clk.t = 12.0
    # one step() = admit + bucketed prefill (first token) + a decode
    # iteration (second token), all stamped at t=12
    assert eng.step() == []
    clk.t = 15.5
    outs = eng.step()           # third token -> finish
    assert [o.request_id for o in outs] == [rid]
    m = outs[0].metrics
    assert m.t_enqueue == 10.0 and m.t_admit == 12.0
    assert m.queue_s == pytest.approx(2.0)
    assert m.ttft_s == pytest.approx(2.0) and outs[0].ttft_s == m.ttft_s
    assert m.t_first_token == 12.0 and m.t_finish == 15.5
    assert m.e2e_s == pytest.approx(5.5)
    assert m.tpot_s == pytest.approx((15.5 - 12.0) / 2)
    assert m.n_generated == 3
    lat = eng.stats()["latency"]
    assert lat["queue_s"]["count"] == 1
    assert lat["queue_s"]["sum"] == pytest.approx(2.0)
    assert lat["ttft_s"]["max"] == pytest.approx(2.0)
    assert lat["e2e_s"]["sum"] == pytest.approx(5.5)
    assert lat["tpot_s"]["mean"] == pytest.approx(1.75)


def test_lifecycle_covers_abort_and_prefix_hit(tiny):
    """The abort path closes the record (with its own counter, not the
    latency histograms); a prefix-hit admission carries cached_tokens into
    the record."""
    cfg, params = tiny
    clk = FakeClock(100.0)
    eng = LLMEngine(params, cfg, num_slots=1, page_size=8, num_pages=17,
                    max_model_len=64, prefill_chunk=8, clock=clk)
    prompt = (np.arange(20, dtype=np.int32) * 7 + 1) % cfg.vocab_size
    rid = eng.add_request(prompt, max_new_tokens=4)
    eng.run()
    # same prompt again: admission maps the cached prefix
    rid2 = eng.add_request(prompt, max_new_tokens=4)
    eng.step()
    out2 = eng.run()[rid2]
    assert out2.metrics.cached_tokens > 0
    assert out2.cached_tokens == out2.metrics.cached_tokens
    # queued abort: never admitted -> no admission stamp, reason recorded
    blocker = eng.add_request(prompt[:9], max_new_tokens=40)
    clk.t = 101.0
    waiting = eng.add_request(prompt[:5], max_new_tokens=4)
    eng.step()
    e2e_before = eng.stats()["latency"]["e2e_s"]["count"]
    clk.t = 103.0
    assert eng.abort(waiting)           # still queued: slot held by blocker
    assert eng.abort(blocker)           # running
    out = eng.run()[waiting]
    assert out.finish_reason == "abort"
    assert out.metrics.t_admit is None and out.metrics.queue_s is None
    assert out.metrics.e2e_s == pytest.approx(2.0)
    st = eng.stats()
    assert st["aborted_requests"] == 2
    assert st["latency"]["e2e_s"]["count"] == e2e_before  # aborts excluded


def test_counters_monotonic_across_abort_and_eviction(spec_eng):
    """No counter ever decreases while the engine churns through prefix
    hits, LRU eviction and a mid-flight abort; the page partition stays
    consistent afterwards."""
    eng = spec_eng
    rng = np.random.RandomState(5)
    shared = rng.randint(0, eng.config.vocab_size, (20,)).astype(np.int32)
    rids = []
    for i in range(8):
        if i % 3 == 0:
            tail = rng.randint(0, eng.config.vocab_size, (i,)).astype(np.int32)
            prompt = np.concatenate([shared, tail]) if i else shared.copy()
        else:
            prompt = rng.randint(0, eng.config.vocab_size,
                                 (int(rng.randint(4, 40)),)).astype(np.int32)
        rids.append(eng.add_request(prompt, max_new_tokens=6))
    prev = eng.metrics.snapshot()["counters"]
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        if steps == 3:
            assert eng.abort(rids[-1])
        cur = eng.metrics.snapshot()["counters"]
        for k, v in cur.items():
            # lazily registered counters (per-priority goodput) appear
            # mid-run at 0 — appearing is fine, decreasing is not
            assert v >= prev.get(k, 0), \
                f"counter {k} decreased: {prev.get(k, 0)} -> {v}"
        prev = cur
    st = eng.stats()
    assert st["aborted_requests"] >= 1
    assert st["prefix_evictions"] >= 1          # pool pressure hit the LRU
    assert st["prefix_evictions"] == prev["prefix_evictions"]  # mirror synced
    assert st["spec_events"] > 0
    eng.cache.check_invariants()


def test_stats_spec_events_recompute_acceptance(spec_eng):
    """Satellite: spec_events is reported, so accepted_per_step is
    recomputable from the stats dict alone."""
    st = spec_eng.stats()
    assert st["spec_events"] > 0
    assert st["accepted_per_step"] == pytest.approx(
        st["spec_emitted_tokens"] / st["spec_events"])


def test_chrome_trace_and_step_timeline(spec_eng, tmp_path):
    """engine.trace(dir) exports a valid chrome trace holding the engine's
    host-phase span names, the step-timeline ring, and a metrics snapshot."""
    eng = spec_eng
    td = tmp_path / "trace"
    with eng.trace(str(td), device=False):
        rng = np.random.RandomState(9)
        for n in (5, 18, 30):
            eng.add_request(rng.randint(0, eng.config.vocab_size,
                                        (n,)).astype(np.int32),
                            max_new_tokens=4)
        eng.run()
    host = json.loads((td / "host_trace.json").read_text())
    names = {e["name"] for e in host["traceEvents"]}
    # fused engine (default): the one-dispatch step emits the fused span in
    # place of the legacy verify/decode/chunk dispatch spans
    assert {"engine.step", "engine.admit", "engine.fused.dispatch",
            "engine.spec.propose", "engine.spec.accept",
            "engine.sample.sync"} <= names
    assert names <= set(ENGINE_SPANS)
    for e in host["traceEvents"]:
        assert e["ph"] == "X" and e["dur"] >= 0
    timeline = json.loads((td / "step_timeline.json").read_text())
    assert timeline and timeline[-1]["step"] >= len(timeline)
    for key in ("decode_batch", "chunk", "verify_dispatches",
                "tokens_emitted", "pages_in_use", "pages_free",
                "pages_evictable", "queued", "running", "prefilling",
                "v", "fused", "dispatches", "sync_ms", "slots"):
        assert key in timeline[-1]
    assert any(r["tokens_emitted"] > 0 for r in timeline)
    snap = json.loads((td / "metrics.json").read_text())
    assert snap["counters"]["decode_tokens"] > 0
    assert snap["proposer"]["propose_calls"] > 0
    # spans are recorded only inside a trace window
    n_before = len(eng.step_trace())
    eng.add_request(np.arange(4, dtype=np.int32), max_new_tokens=2)
    eng.run()
    assert len(eng.step_trace()) > n_before


def test_trace_rides_outer_profiler(spec_eng, tmp_path):
    """engine.trace() nested inside a user Profiler must not wipe the outer
    event buffer or stop the outer recording — it rides it and snapshots."""
    from paddle_tpu.profiler import Profiler, RecordEvent, is_recording
    from paddle_tpu.profiler import profiler as prof_mod
    eng = spec_eng
    with Profiler(timer_only=True):
        with RecordEvent("outer.before"):
            pass
        with eng.trace(str(tmp_path / "t"), device=False):
            eng.add_request(np.arange(4, dtype=np.int32), max_new_tokens=2)
            eng.run()
        assert is_recording()           # outer recording still live
        with RecordEvent("outer.after"):
            pass
        names = {e.name for e in prof_mod._events}
        assert {"outer.before", "engine.step", "outer.after"} <= names
    host = json.loads((tmp_path / "t" / "host_trace.json").read_text())
    snap_names = {e["name"] for e in host["traceEvents"]}
    assert "engine.step" in snap_names and "outer.before" in snap_names


def test_step_trace_ring_bounded(tiny):
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                    trace_ring=4)
    eng.add_request(np.arange(3, dtype=np.int32), max_new_tokens=10)
    eng.run()
    trace = eng.step_trace()
    assert len(trace) == 4                      # ring capped
    assert trace[-1]["step"] > 4                # but steps kept counting
    eng.reset_counters()
    assert eng.step_trace() == []
    assert eng.stats()["decode_tokens"] == 0


def test_stats_execs_fallback_attribute_error_only(spec_eng, monkeypatch):
    """Satellite: a missing _cache_size falls back to the tracked count, but
    a REAL failure inside _cache_size propagates instead of being silently
    absorbed into the fallback number."""
    class _NoSize:
        pass

    class _Boom:
        def _cache_size(self):
            raise RuntimeError("bug inside the executable cache")

    monkeypatch.setattr(spec_eng, "_decode_fn", _NoSize())
    st = spec_eng.stats()       # fallback path: tracked approximation
    assert st["decode_executables"] in (0, 1)
    monkeypatch.setattr(spec_eng, "_decode_fn", _Boom())
    with pytest.raises(RuntimeError, match="bug inside"):
        spec_eng.stats()


GOLDEN_STATS_KEYS = frozenset({
    # frozen pre-observability surface (PRs 1-4): benches and tests consume
    # these — removing or renaming any of them is an API break
    "decode_executables", "verify_executables", "prefill_executables",
    "copy_executables", "buckets", "prefill_chunk", "spec_len", "mp",
    "decode_iterations", "decode_tokens", "verify_steps",
    "spec_drafted_tokens", "spec_accepted_tokens", "spec_emitted_tokens",
    "spec_backoffs", "accepted_per_step", "prefill_chunks",
    "prefilled_tokens", "prefix_cached_tokens", "prefix_hit_requests",
    "prefix_hit_rate", "cow_page_copies", "pages_in_use", "pages_free",
    "pages_evictable", "prefix_evictions", "kv_token_capacity",
    "dense_token_footprint", "queued", "prefilling", "running",
})
NEW_STATS_KEYS = frozenset({
    # added by the observability PR
    "engine_steps", "spec_events", "finished_requests", "aborted_requests",
    "latency",
}) | frozenset({
    # added by the oversubscription PR (overload surface)
    "swap_executables", "admission", "preempt", "preemptions",
    "preempt_swaps", "preempt_recomputes", "swapped_pages", "swap_ms",
    "recomputed_tokens", "timeouts", "rejected_requests", "swapped",
    "kv_pages_swapped", "kv_pool_pressure",
}) | frozenset({
    # added by the quantized-serving PR (weight/kv int8 + intake admission)
    "weight_dtype", "kv_dtype", "kv_pool_bytes", "intake_swap_rejects",
}) | frozenset({
    # added by the observability-plane PR (SLO block: deadline attainment +
    # per-priority-class goodput — the router's SLO layer input)
    "slo",
}) | frozenset({
    # added by the health & signals PR: windowed rates, the folded health
    # state, and the live roofline account
    "rates", "health", "roofline",
}) | frozenset({
    # added by the KV tiering PR: per-tier occupancy + spill/restore traffic
    # + the rolling-hash partial-index hit counter
    "kv_tier",
}) | frozenset({
    # added by the disaggregated-serving PR: the engine's fleet role
    # (None / "prefill" / "decode") so health and routing can label it
    "role",
})


def test_stats_keyset_backcompat_golden(spec_eng):
    """Every pre-observability stats() key survives byte-for-byte, and the
    full key set is exactly golden + the documented additions — an
    accidental key (or a dropped one) fails here before a bench does."""
    keys = set(spec_eng.stats())
    assert GOLDEN_STATS_KEYS <= keys
    assert keys == GOLDEN_STATS_KEYS | NEW_STATS_KEYS
    lat = spec_eng.stats()["latency"]
    assert set(lat) == {"queue_s", "ttft_s", "tpot_s", "e2e_s", "step_s"}
    for summ in lat.values():
        assert set(summ) == {"count", "sum", "mean", "min", "max",
                             "p50", "p90", "p99"}


# ---------------------------------------------------------------------------
# per-request tracing: chrome export + exemplar round-trip (ISSUE 12)
# ---------------------------------------------------------------------------

def test_request_trace_chrome_export_phases():
    """Pure-host chrome rendering: lifecycle stamps become the root span +
    queued/prefill/decode phase children with exact (relative-us) geometry;
    every raw event rides along as an instant."""
    tr = RequestTrace(7)
    tr.event(1.0, "enqueue", prompt_len=4)
    tr.event(2.0, "admit", slot=0)
    tr.event(3.0, "first_token")
    tr.event(5.0, "finish", reason="stop", n_generated=2)
    tree = tr.to_chrome()
    json.dumps(tree)                            # serializable as-is
    evs = tree["traceEvents"]
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert spans["request/7"]["dur"] == pytest.approx(4e6)
    assert spans["queued"]["ts"] == 0.0
    assert spans["queued"]["dur"] == pytest.approx(1e6)
    assert spans["prefill"]["ts"] == pytest.approx(1e6)
    assert spans["prefill"]["dur"] == pytest.approx(1e6)
    assert spans["decode"]["ts"] == pytest.approx(2e6)
    assert spans["decode"]["dur"] == pytest.approx(2e6)
    instants = [e for e in evs if e["ph"] == "i"]
    assert len(instants) == len(tr.events)
    assert instants[0]["args"] == {"prompt_len": 4}
    assert all(e["tid"] == 7 for e in evs)      # one track per request
    # a phase never reached is absent: abort while queued has only "queued"
    tr2 = RequestTrace(8)
    tr2.event(1.0, "enqueue")
    tr2.event(2.0, "finish", reason="abort")
    names = {e["name"] for e in tr2.to_chrome()["traceEvents"]
             if e["ph"] == "X"}
    assert names == {"request/8", "queued"}
    # empty timeline renders a valid empty tree (never KeyErrors)
    assert RequestTrace(9).to_chrome() == {"traceEvents": [],
                                           "displayTimeUnit": "ms"}


def test_exemplar_roundtrip_exposition_to_request(tiny):
    """observe -> exposition -> parse -> rid: every exemplar in the live
    exposition carries the obs-server handle and resolves through
    export_request_trace to the request's own span tree."""
    from tools.check_metrics import check_exposition, parse_prometheus_full
    cfg, params = tiny
    clk = FakeClock(5.0)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    clock=clk)
    rid = eng.add_request(np.arange(6, dtype=np.int32), max_new_tokens=3)
    clk.t = 6.0
    out = eng.run()[rid]
    names = [e["name"] for e in out.trace.events]
    assert names[0] == "enqueue" and names[-1] == "finish"
    assert "admit" in names and "first_token" in names
    text = eng.metrics.to_prometheus(exemplars=True)
    errs = []
    check_exposition(text, errs)
    assert not errs, errs
    _, exemplars = parse_prometheus_full(text)
    assert exemplars, "no exemplar in the exposition"
    for (name, _), (lbls, _v) in exemplars.items():
        assert name.endswith("_bucket")
        assert lbls["trace"] == f'/requests/{lbls["request_id"]}'
        tree = eng.export_request_trace(int(lbls["request_id"]))
        assert tree is not None and tree["traceEvents"]
    assert rid in {int(l["request_id"]) for l, _ in exemplars.values()}
    # the resolved tree is the chrome rendering of the same timeline
    tnames = {e["name"]
              for e in eng.export_request_trace(rid)["traceEvents"]}
    assert {f"request/{rid}", "queued", "prefill", "decode",
            "enqueue", "finish"} <= tnames
    # exemplars follow the dialect by default: the `# {...}` suffix is
    # OpenMetrics-only syntax, so a bare to_prometheus() is pure 0.0.4 a
    # stock parser can scrape, and explicit exemplars=False strips them
    # from any dialect
    assert " # {" not in eng.metrics.to_prometheus()
    assert " # {" in eng.metrics.to_prometheus(openmetrics=True)
    assert " # {" not in eng.metrics.to_prometheus(openmetrics=True,
                                                   exemplars=False)


def test_request_tracing_off_strips_surface(tiny):
    """request_tracing=False: no timelines, no /requests resolution, no
    exemplars — but every histogram still observes (the A/B axis the bench's
    <2% overhead bar runs on)."""
    from tools.check_metrics import parse_prometheus_full
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    request_tracing=False)
    rid = eng.add_request(np.arange(6, dtype=np.int32), max_new_tokens=3)
    out = eng.run()[rid]
    assert out.trace is None
    assert eng.export_request_trace(rid) is None
    samples, exemplars = parse_prometheus_full(
        eng.metrics.to_prometheus(exemplars=True))
    assert not exemplars        # none to emit even when asked for
    assert samples["llm_engine_ttft_seconds_count"][0][1] >= 1


def test_trace_retention_bounds_retired_timelines(tiny):
    """`trace_retention` caps how many RETIRED timelines the output ledger
    holds: past the cap the oldest retired trace drops (its RequestOutput
    keeps its tokens and metrics), newer ones keep resolving — the bound
    that keeps an always-on plane from growing host memory forever on a
    long-running server.  None retains everything."""
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    trace_retention=2)
    rids = [eng.add_request(np.arange(4 + i, dtype=np.int32),
                            max_new_tokens=2) for i in range(3)]
    outs = eng.run()
    # 3 retirements, cap 2: the oldest timeline dropped, the rest resolve
    assert eng.export_request_trace(rids[0]) is None
    assert eng.export_request_trace(rids[1])["traceEvents"]
    assert eng.export_request_trace(rids[2])["traceEvents"]
    # the evicted request's OUTPUT survives, tokens intact
    assert outs[rids[0]].finish_reason in ("stop", "length")
    assert outs[rids[0]].trace is None
    assert len(outs[rids[0]].token_ids) >= 1
    eng2 = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                     trace_retention=None)
    r2 = [eng2.add_request(np.arange(4, dtype=np.int32), max_new_tokens=2)
          for _ in range(3)]
    eng2.run()
    assert all(eng2.export_request_trace(x) is not None for x in r2)
    with pytest.raises(ValueError, match="trace_retention"):
        LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64,
                  trace_retention=-1)


@pytest.mark.parametrize("mode", ["swap", "recompute"])
def test_timeline_exact_across_preempt_resume(tiny, mode):
    """Fake-clock exactness through a forced preempt/resume cycle, both
    eviction policies: a swap victim restores in place (swap_out -> swap_in,
    no re-admission), a recompute victim re-enters through a second
    admit(resume=True); stamps ride the engine clock monotonically and the
    survivor's timeline stays preemption-free."""
    cfg, params = tiny
    clk = FakeClock(100.0)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    prefill_chunk=8, admission="optimistic", preempt=mode,
                    clock=clk, fault_plan=FaultPlan(pressure_steps=(4,)))
    lo = eng.add_request(np.arange(4, dtype=np.int32), max_new_tokens=20,
                         priority=0)
    hi = eng.add_request(np.arange(4, 6, dtype=np.int32), max_new_tokens=20,
                         priority=1)
    while eng.has_work:
        clk.t += 1.0
        eng.step()
    st = eng.stats()
    assert st["preemptions"] >= 1
    ev = eng._outputs[lo].trace.events
    names = [e["name"] for e in ev]
    assert names[0] == "enqueue" and ev[0]["t"] == 100.0
    assert names[-1] == "finish" and ev[-1]["reason"] == "length"
    assert ev[-1]["n_generated"] == len(eng._outputs[lo].token_ids)
    for key in ("grow_fail", "preempt", "first_token"):
        assert key in names, f"missing {key}: {names}"
    assert ev[names.index("preempt")]["kind"] == mode
    if mode == "swap":
        assert st["preempt_swaps"] >= 1
        assert "swap_out" in names and "swap_in" in names
        assert names.index("preempt") < names.index("swap_out") \
            < names.index("swap_in")
        assert "slot" in ev[names.index("swap_in")]
        assert names.count("admit") == 1    # in-place restore, no re-admit
    else:
        assert "swap_out" not in names and "swap_in" not in names
        assert names.count("admit") == 2    # first admission + replay
        admits = [e for e in ev if e["name"] == "admit"]
        assert admits[0]["resume"] is False and admits[1]["resume"] is True
        assert names.index("preempt") < names.index("admit", 1 +
                                                    names.index("admit"))
    ts = [e["t"] for e in ev]
    assert ts == sorted(ts)                 # engine clock is the only stamp
    # survivor: admitted once, never preempted
    hi_names = [e["name"] for e in eng._outputs[hi].trace.events]
    assert "preempt" not in hi_names and hi_names.count("admit") == 1
    # post-retirement resolution still works (trace rides the output)
    assert eng.export_request_trace(lo)["traceEvents"]


def test_timeline_and_slo_across_timeout(tiny):
    """Deadline expiry: the timeline closes with finish(reason=timeout)
    stamped at the expiry-scan clock; SLO accounting lands the miss in the
    attainment denominator while the latency histograms keep excluding it;
    goodput credits final tokens to the finisher's priority class only."""
    cfg, params = tiny
    clk = FakeClock(10.0)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=17,
                    max_model_len=64, clock=clk, double_buffer=False)
    ok = eng.add_request(np.arange(5, dtype=np.int32), max_new_tokens=3,
                         priority=1, deadline_s=1000.0)
    clk.t = 11.0
    eng.run()
    late = eng.add_request(np.arange(7, dtype=np.int32), max_new_tokens=50,
                           deadline_s=5.0)
    eng.step()                              # admitted, decoding
    e2e_before = eng.stats()["latency"]["e2e_s"]["count"]
    clk.t = 40.0                            # far past enqueue + 5s
    eng.step()
    out = eng._outputs[late]
    assert out.finish_reason == "timeout"
    fin = out.trace.events[-1]
    assert fin["name"] == "finish" and fin["reason"] == "timeout"
    assert fin["t"] == 40.0
    slo = eng.stats()["slo"]
    assert slo["deadline_requests"] == 2 and slo["deadline_met"] == 1
    assert slo["deadline_attainment"] == pytest.approx(0.5)
    assert slo["goodput_tokens_by_priority"] == {1: 3}
    # timeouts stay excluded from the latency SLO histograms
    assert eng.stats()["latency"]["e2e_s"]["count"] == e2e_before


def test_reset_counters_mid_trace_window(tiny, tmp_path):
    """The audited reset-vs-open-capture contract (engine.reset_counters
    docstring): a reset inside an engine.trace window neither corrupts the
    chrome export nor leaves a stale exemplar handle — cleared exemplars
    vanish from the exposition, and post-reset observations re-attach
    handles that resolve."""
    from tools.check_metrics import parse_prometheus_full
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64)
    td = tmp_path / "trace"
    with eng.trace(str(td), device=False):
        eng.add_request(np.arange(5, dtype=np.int32), max_new_tokens=3)
        eng.run()
        _, exemplars = parse_prometheus_full(
            eng.metrics.to_prometheus(exemplars=True))
        assert exemplars                    # attached pre-reset
        eng.reset_counters()
        # exemplars cleared WITH the counts: no handle survives a reset
        _, exemplars = parse_prometheus_full(
            eng.metrics.to_prometheus(exemplars=True))
        assert not exemplars
        rid2 = eng.add_request(np.arange(7, dtype=np.int32),
                               max_new_tokens=3)
        eng.run()
    # the chrome export survived the mid-window reset
    host = json.loads((td / "host_trace.json").read_text())
    assert host["traceEvents"]
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in host["traceEvents"])
    # step timeline holds only post-reset records (warmup-exclusion
    # semantics), and stays valid JSON
    timeline = json.loads((td / "step_timeline.json").read_text())
    assert timeline and all("step" in r for r in timeline)
    # post-reset exemplars point at post-reset requests only, and resolve
    _, exemplars = parse_prometheus_full(
        eng.metrics.to_prometheus(exemplars=True))
    rids = {int(l["request_id"]) for l, _ in exemplars.values()}
    assert rids == {rid2}
    assert eng.export_request_trace(rid2)["traceEvents"]


# ---------------------------------------------------------------------------
# fleet aggregation: merge math + labeled re-exposition (ISSUE 12)
# ---------------------------------------------------------------------------

def test_registry_merge_counter_histogram_goldens():
    """merge() vs hand-computed goldens: counter sum, gauge fold by its
    declared agg (sum for levels, MAX for ratio gauges — a sum of
    per-replica fractions would read >100% on a healthy fleet), histogram
    bucket-wise add with min/max/count/sum folded and last-merged exemplar
    per bucket; disjoint names union; empty merges are identities."""
    a = MetricsRegistry(namespace="m")
    b = MetricsRegistry(namespace="m")
    a.counter("c").inc(3)
    b.counter("c").inc(4)
    b.counter("only_b").inc(5)
    a.gauge("g").set(2.0)
    b.gauge("g").set(0.5)
    a.gauge("pressure", agg="max").set(0.3)
    b.gauge("pressure", agg="max").set(0.7)
    ha = a.histogram("h", buckets=[1.0, 2.0])
    hb = b.histogram("h", buckets=[1.0, 2.0])
    ha.observe(0.5, exemplar={"request_id": "1"})
    ha.observe(1.5)
    hb.observe(1.7, exemplar={"request_id": "9"})
    hb.observe(9.0)
    agg = MetricsRegistry(namespace="m").merge(a).merge(b)
    snap = agg.snapshot()
    assert snap["counters"] == {"c": 7, "only_b": 5}
    assert snap["gauges"]["g"] == pytest.approx(2.5)
    assert snap["gauges"]["pressure"] == pytest.approx(0.7)   # max, not 1.0
    with pytest.raises(ValueError, match="agg"):
        MetricsRegistry().gauge("bad", agg="mean")
    h = agg.get("h")
    assert h.counts == [1, 2] and h.overflow == 1
    assert h.count == 4 and h.sum == pytest.approx(0.5 + 1.5 + 1.7 + 9.0)
    assert h.min == 0.5 and h.max == 9.0
    assert h.exemplars[0] == ({"request_id": "1"}, 0.5)
    assert h.exemplars[1] == ({"request_id": "9"}, 1.7)   # last-merged wins
    assert h.exemplars[2] is None
    # empty-registry identities, both directions
    empty = MetricsRegistry(namespace="m")
    assert empty.merge(MetricsRegistry(namespace="m")).snapshot() == \
        MetricsRegistry(namespace="m").snapshot()
    assert MetricsRegistry(namespace="m").merge(a).snapshot() == a.snapshot()
    before = agg.snapshot()
    assert agg.merge(MetricsRegistry(namespace="m")).snapshot() == before


def test_exemplar_label_escape_roundtrip():
    """Label values survive exposition escaping byte-for-byte — including
    the adversarial cases for ordered .replace unescaping (a literal
    backslash before 'n', escaped quotes, real newlines)."""
    from tools.check_metrics import parse_prometheus_full
    tricky = 'back\\slash "quote" bs-n\\nreal\nnewline'
    reg = MetricsRegistry()
    reg.histogram("h", buckets=[1.0]).observe(0.5, exemplar={"v": tricky})
    _, exemplars = parse_prometheus_full(reg.to_prometheus(exemplars=True))
    (labels, value), = exemplars.values()
    assert labels == {"v": tricky}
    assert value == 0.5


def test_registry_merge_conflicts_raise():
    """Mismatched bucket edges, name/type conflicts and a callback gauge on
    the aggregate side all refuse loudly instead of merging garbage."""
    a = MetricsRegistry()
    a.histogram("h", buckets=[1.0, 2.0]).observe(0.5)
    bad_edges = MetricsRegistry()
    bad_edges.histogram("h", buckets=[1.0, 3.0])
    with pytest.raises(ValueError, match="bucket edges differ"):
        bad_edges.merge(a)
    bad_type = MetricsRegistry()
    bad_type.gauge("h").set(1.0)
    with pytest.raises(TypeError):
        bad_type.merge(a)
    live = MetricsRegistry()
    live.gauge("g", lambda: 7)              # callback gauge: read-only
    src = MetricsRegistry()
    src.gauge("g").set(1.0)
    with pytest.raises(ValueError):
        live.merge(src)
    # but a callback gauge on the SOURCE side merges by value
    agg = MetricsRegistry()
    agg.merge(live)
    assert agg.get("g").value == 7


def test_fleet_metrics_exposition_and_snapshot():
    """FleetMetrics over two registries (one disjoint metric): per-engine
    labeled series grouped per family, llm_fleet_* totals equal to the
    member sums, and the whole exposition passes the CI checker."""
    from tools.check_metrics import check_exposition, parse_prometheus
    r0 = MetricsRegistry(namespace="llm_engine")
    r1 = MetricsRegistry(namespace="llm_engine")
    r0.counter("decode_tokens").inc(10)
    r1.counter("decode_tokens").inc(32)
    r1.counter("only_e1").inc(2)
    r0.histogram("ttft_seconds", buckets=[0.1, 1.0]).observe(
        0.05, exemplar={"request_id": "3", "trace": "/requests/3"})
    r1.histogram("ttft_seconds", buckets=[0.1, 1.0]).observe(0.5)
    fleet = FleetMetrics().add("e0", r0).add("e1", r1)
    text = fleet.to_prometheus(exemplars=True)
    errs = []
    check_exposition(text, errs)
    assert not errs, errs
    samples = parse_prometheus(text)
    per = dict(samples["llm_engine_decode_tokens_total"])
    assert per == {'{engine="e0"}': 10, '{engine="e1"}': 32}
    assert samples["llm_fleet_decode_tokens_total"][0][1] == 42
    assert dict(samples["llm_engine_only_e1_total"]) == {'{engine="e1"}': 2}
    assert samples["llm_fleet_only_e1_total"][0][1] == 2
    assert samples["llm_fleet_ttft_seconds_count"][0][1] == 2
    # member exemplars survive the labeled re-exposition, with the trace
    # handle scoped to the member (request ids are per-engine counters)
    assert 'request_id="3"' in text
    assert 'trace="/requests/3?engine=e0"' in text
    # and the default fleet exposition follows the dialect: no exemplars
    assert " # {" not in fleet.to_prometheus()
    snap = fleet.snapshot()
    assert set(snap) == {"fleet", "engines"}
    assert set(snap["engines"]) == {"e0", "e1"}
    assert snap["fleet"]["counters"]["decode_tokens"] == 42
    assert snap["engines"]["e0"]["counters"]["decode_tokens"] == 10
    with pytest.raises(TypeError):
        FleetMetrics().add("x", object())


# ---------------------------------------------------------------------------
# HTTP observability plane + postmortem debug bundle (ISSUE 12)
# ---------------------------------------------------------------------------

def _http_get(url, accept=None):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        url, headers={"Accept": accept} if accept else {})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def test_obs_server_endpoint_smoke(tiny):
    """All five routes over a real loopback socket on an ephemeral port:
    /metrics parses with exemplars, /stats carries the SLO block,
    /requests/<rid> serves the span tree (404 unknown, 400 malformed),
    /debug is a valid bundle, /healthz answers — and close() actually tears
    the daemon-thread listener down."""
    import urllib.error

    from paddle_tpu.inference.obs_server import ObservabilityServer
    from tools.check_metrics import (REQUIRED_DEBUG_BUNDLE_KEYS,
                                     check_exposition, parse_prometheus_full)
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64)
    rid = eng.add_request(np.arange(6, dtype=np.int32), max_new_tokens=3)
    eng.run()
    with ObservabilityServer(eng) as srv:
        assert srv.port > 0 and srv.url.startswith("http://127.0.0.1:")
        # OpenMetrics negotiation: exemplars + # EOF on the wire
        code, text = _http_get(srv.url + "/metrics",
                               accept="application/openmetrics-text")
        assert code == 200 and text.endswith("# EOF\n")
        errs = []
        check_exposition(text, errs)
        assert not errs, errs
        assert parse_prometheus_full(text)[1]       # exemplars on the wire
        # plain scrape: 0.0.4 text, exemplar-free (stock Prometheus rejects
        # the suffix outside openmetrics mode)
        code, plain = _http_get(srv.url + "/metrics")
        assert code == 200 and " # {" not in plain
        assert "# EOF" not in plain
        errs = []
        check_exposition(plain, errs)
        assert not errs, errs
        code, text = _http_get(srv.url + "/stats")
        assert code == 200 and "slo" in json.loads(text)
        code, text = _http_get(srv.url + f"/requests/{rid}")
        assert code == 200 and json.loads(text)["traceEvents"]
        assert _http_get(srv.url + "/requests/424242")[0] == 404
        assert _http_get(srv.url + "/requests/nope")[0] == 400
        assert _http_get(srv.url + "/nosuch")[0] == 404
        code, text = _http_get(srv.url + "/healthz")
        health = json.loads(text)
        assert code == 200 and health["state"] in ("ok", "degraded")
        assert "signals" in health and "reasons" in health  # not the old stub
        # the 404 route list advertises exactly the served routes
        code, text = _http_get(srv.url + "/nosuch")
        assert code == 404
        assert set(json.loads(text)["routes"]) == {
            "/metrics", "/stats", "/requests/<rid>", "/debug", "/healthz"}
        code, text = _http_get(srv.url + "/debug")
        assert code == 200
        assert REQUIRED_DEBUG_BUNDLE_KEYS <= set(json.loads(text))
        url = srv.url
    with pytest.raises((ConnectionError, urllib.error.URLError)):
        _http_get(url + "/healthz")


def test_obs_server_fleet_mode(tiny):
    """Fleet mode: /metrics re-exposes members under engine labels plus
    llm_fleet totals, /stats and /debug key by member label, and
    /requests/<rid> disambiguates colliding per-engine request ids —
    ?engine= (what fleet exemplar handles carry) scopes the lookup, a bare
    colliding rid gets 300 with the candidate handles instead of an
    arbitrary member's timeline.  Constructor rejects ambiguous
    engine+fleet wiring."""
    from paddle_tpu.inference.obs_server import ObservabilityServer
    cfg, params = tiny
    e0 = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64)
    e1 = LLMEngine(params, cfg, num_slots=1, page_size=8, max_model_len=64)
    # SAME rid on both members: per-engine counters both start at 0
    rid0 = e0.add_request(np.arange(7, dtype=np.int32), max_new_tokens=2)
    e0.run()
    rid = e1.add_request(np.arange(5, dtype=np.int32), max_new_tokens=2)
    e1.run()
    assert rid0 == rid
    fleet = FleetMetrics().add("e0", e0).add("e1", e1)
    with ObservabilityServer(fleet=fleet) as srv:
        code, text = _http_get(srv.url + "/metrics",
                               accept="application/openmetrics-text")
        assert code == 200
        assert 'engine="e0"' in text and 'engine="e1"' in text
        assert "llm_fleet_" in text
        # fleet exemplar handles are member-scoped, and resolve as served
        assert f'trace="/requests/{rid}?engine=e1"' in text
        def enqueue_prompt_len(tree):
            enq = [e for e in tree["traceEvents"] if e["name"] == "enqueue"]
            return enq[0]["args"]["prompt_len"]

        code, text = _http_get(srv.url + f"/requests/{rid}?engine=e1")
        assert code == 200 and enqueue_prompt_len(json.loads(text)) == 5
        code, text = _http_get(srv.url + f"/requests/{rid}?engine=e0")
        assert code == 200 and enqueue_prompt_len(json.loads(text)) == 7
        # a bare colliding rid is ambiguous: candidates, not a silent guess
        code, text = _http_get(srv.url + f"/requests/{rid}")
        assert code == 300
        body = json.loads(text)
        assert body["engines"] == ["e0", "e1"]
        assert f"/requests/{rid}?engine=e1" in body["handles"]
        assert _http_get(srv.url + f"/requests/{rid}?engine=nosuch")[0] == 404
        code, text = _http_get(srv.url + "/stats")
        st = json.loads(text)
        assert code == 200 and set(st) == {"e0", "e1"}
        assert st["e1"]["finished_requests"] == 1
        code, text = _http_get(srv.url + "/debug")
        assert code == 200 and set(json.loads(text)) == {"e0", "e1"}
    with pytest.raises(ValueError):
        ObservabilityServer(e0, fleet=fleet)
    with pytest.raises(ValueError):
        ObservabilityServer()


def test_debug_bundle_valid_after_forced_fault_crash(tiny, tmp_path):
    """bench_serve's crash hook, reproduced at the engine API: a hard (non-
    degradable) fault escapes step() mid-flight with rich scheduler state,
    and dump_debug_bundle still writes a valid, schema-complete JSON
    postmortem — request states with timelines, step ring, pool levels."""
    from tools.check_metrics import REQUIRED_DEBUG_BUNDLE_KEYS
    cfg, params = tiny

    class _HardFault(FaultPlan):
        # a non-FaultInjected error cannot be degraded to recompute: it
        # escapes the engine exactly like a real d2h wreck would
        def d2h(self):
            raise RuntimeError("hard d2h crash")

    eng = LLMEngine(params, cfg, num_slots=6, page_size=8, num_pages=9,
                    max_model_len=64, prefill_chunk=8,
                    admission="optimistic", preempt="swap",
                    fault_plan=_HardFault(pressure_steps=(3,)))
    rng = np.random.RandomState(7)
    for n in (5, 9, 14, 20, 6, 11):
        eng.add_request(rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32),
                        max_new_tokens=24)
    with pytest.raises(RuntimeError, match="hard d2h crash"):
        while eng.has_work:
            eng.step()
    path = eng.dump_debug_bundle(str(tmp_path / "bundle"))
    with open(path) as f:
        bundle = json.load(f)
    assert REQUIRED_DEBUG_BUNDLE_KEYS <= set(bundle)
    assert bundle["engine"]["request_tracing"] is True
    reqs = bundle["requests"]
    assert reqs, "no request states in the postmortem"
    states = {r["state"] for r in reqs.values()}
    assert states <= {"queued", "prefilling", "running", "finished"}
    assert any(r["events"] for r in reqs.values())
    assert bundle["step_trace"] and isinstance(bundle["step_trace"], list)
    assert isinstance(bundle["pool"]["pages_in_use"], int)
    assert "slo" in bundle["stats"]
    assert bundle["metrics"]["counters"]["preemptions"] >= 1


# ---------------------------------------------------------------------------
# health & perf signal plane (ISSUE 13): windowed rates, burn-rate health,
# live roofline drift, serving-bench trajectory
# ---------------------------------------------------------------------------

def test_rate_window_golden_values():
    """RateWindow math is exact under an injectable clock: empty ring,
    single sample, live right-edge reads, young-ring oldest-sample
    reference, in-window reference selection, and idle decay to 0.0."""
    from paddle_tpu.inference.metrics import RateWindow
    t = [0.0]
    v = [0]
    rw = RateWindow("r", lambda: v[0], lambda: t[0],
                    (("10s", 10.0), ("1m", 60.0)), min_interval_s=0.0)
    assert rw.rate(10.0) == 0.0                 # empty ring: no reference
    rw.sample()                                 # (0, 0)
    assert rw.rate(10.0) == 0.0                 # single sample, zero elapsed
    t[0], v[0] = 5.0, 50
    # live read against the ring: (50 - 0) / (5 - 0) — no sample needed
    assert rw.rate(10.0) == pytest.approx(10.0)
    rw.sample()                                 # (5, 50)
    t[0], v[0] = 8.0, 80
    # ring younger than the window: the OLDEST sample is the reference
    assert rw.rate(10.0) == pytest.approx(10.0)     # 80 / 8
    assert rw.delta(10.0) == pytest.approx(80.0)
    rw.sample()                                 # (8, 80)
    t[0] = 16.0
    # newest sample at or before now-10 = (5, 50): (80-50)/(16-5)
    assert rw.rate(10.0) == pytest.approx(30.0 / 11.0)
    assert rw.delta(10.0) == pytest.approx(30.0)
    # the 1m window still spans everything: 80 events over 16 s
    assert rw.rate(60.0) == pytest.approx(5.0)
    # idle decay: the counter stopped, so every window reads exactly 0.0
    # with no further samples
    t[0] = 100.0
    assert rw.rate(10.0) == 0.0
    assert rw.rate(60.0) == 0.0
    assert rw.rates() == {"10s": 0.0, "1m": 0.0}
    with pytest.raises(ValueError):
        RateWindow("bad", lambda: 0, lambda: 0.0, (("w", -1.0),))


def test_rate_window_reset_and_pruning():
    """A counter observed DECREASING (reset underneath the ring) restarts
    the window instead of reporting a negative rate; pruning keeps exactly
    one reference sample beyond the horizon; registry reset clears rings."""
    from paddle_tpu.inference.metrics import MetricsRegistry, RateWindow
    t = [0.0]
    v = [0]
    rw = RateWindow("r", lambda: v[0], lambda: t[0], (("10s", 10.0),),
                    min_interval_s=0.0)
    rw.sample()
    t[0], v[0] = 5.0, 50
    rw.sample()
    v[0] = 3                                    # counter reset mid-window
    assert rw.rate(10.0) == 0.0                 # never negative
    assert not rw._samples                      # ring restarted
    rw.sample()                                 # (5, 3): fresh baseline
    t[0], v[0] = 7.0, 13
    assert rw.rate(10.0) == pytest.approx(5.0)  # (13-3)/2 post-reset only
    # sample() detects the reset too (no rate() call needed)
    v[0] = 0
    rw.sample()
    assert list(rw._samples) == [(7.0, 0.0)]
    # pruning: samples past the horizon drop, keeping the newest one at or
    # beyond it as the exact reference for the largest window
    for i in range(1, 8):
        t[0], v[0] = 7.0 + 2.0 * i, 10 * i
        rw.sample()
    assert all(tt > t[0] - 10.0 for tt, _ in list(rw._samples)[1:])
    assert rw._samples[0][0] <= t[0] - 10.0     # the kept reference
    # forced samples anchor eventful bursts WITHOUT growing the ring:
    # inside the throttle interval they slide the newest entry forward
    # (when it is itself within the interval of its predecessor)
    rw3 = RateWindow("f", lambda: v[0], lambda: t[0], (("10s", 10.0),),
                     min_interval_s=1.0)
    t[0], v[0] = 100.0, 0
    rw3.sample()
    t[0], v[0] = 100.2, 2
    rw3.sample(force=True)              # appended (lone predecessor)
    t[0], v[0] = 100.4, 4
    rw3.sample(force=True)              # slides the 100.2 anchor
    t[0], v[0] = 100.6, 6
    rw3.sample(force=True)              # slides again: ring stays at 2
    assert list(rw3._samples) == [(100.0, 0.0), (100.6, 6.0)]
    t[0] = 100.8
    rw3.sample()                        # unforced inside the interval: no-op
    assert list(rw3._samples) == [(100.0, 0.0), (100.6, 6.0)]
    # the anchor is exact: once the window passes the burst, rate reads 0
    t[0] = 200.0
    assert rw3.rate(10.0) == 0.0
    # registry wiring: per-window pull gauges + reset clears the ring
    reg = MetricsRegistry(clock=lambda: t[0])
    c = reg.counter("events")
    rw2 = reg.rate_window("events_per_sec", lambda: c.value,
                          (("10s", 10.0),), min_interval_s=0.0)
    assert reg.rate_window("events_per_sec", lambda: -1) is rw2  # idempotent
    t0 = t[0]
    reg.sample_rates()
    c.inc(40)
    t[0] = t0 + 4.0
    assert reg.snapshot()["gauges"]["events_per_sec_10s"] == \
        pytest.approx(10.0)
    assert "events_per_sec_10s" in reg.to_prometheus()
    reg.reset()
    assert not rw2._samples and c.value == 0


def test_engine_rates_exact_under_fake_clock(tiny):
    """stats()['rates'] golden values through the engine: the reset-time
    seed sample makes a young window read exactly events-since-reset over
    elapsed-since-reset; idle decay and the reset_counters contract hold."""
    cfg, params = tiny
    clk = FakeClock(50.0)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    clock=clk, double_buffer=False)
    eng.add_request(np.arange(5, dtype=np.int32), max_new_tokens=4)
    while eng.has_work:
        clk.t += 1.0
        eng.step()
    st = eng.stats()
    elapsed = clk.t - 50.0
    tokens = st["decode_tokens"]            # decode-emitted (first token is
    assert tokens >= 3                      # prefill's, not counted here)
    for w in ("10s", "1m", "5m"):
        # span < every window: the seed sample at t=50 is the reference
        assert st["rates"]["tokens_per_sec"][w] == \
            pytest.approx(tokens / elapsed)
    assert st["rates"]["admits_per_sec"]["5m"] == pytest.approx(1 / elapsed)
    assert st["rates"]["preemptions_per_sec"]["10s"] == 0.0
    # the same numbers ride the exposition as pull gauges
    snap = eng.metrics.snapshot()["gauges"]
    assert snap["tokens_per_sec_10s"] == pytest.approx(tokens / elapsed)
    # idle decay: the engine stops, rates fall to exactly 0.0 untouched
    clk.t += 400.0
    assert eng.stats()["rates"]["tokens_per_sec"]["5m"] == 0.0
    # reset mid-life: rings restart with the counters (the PR-12 reset
    # contract extended) — post-reset rates count post-reset events only
    eng.reset_counters()
    t_reset = clk.t
    eng.add_request(np.arange(3, dtype=np.int32), max_new_tokens=2)
    while eng.has_work:
        clk.t += 2.0
        eng.step()
    st = eng.stats()
    assert st["decode_tokens"] >= 1
    assert st["rates"]["tokens_per_sec"]["5m"] == \
        pytest.approx(st["decode_tokens"] / (clk.t - t_reset))


def test_slo_burn_rates_and_health_under_clock_skew(tiny):
    """Burn-rate edges under the fake clock: no deadline traffic burns 0
    (ok); on-time finishes burn 0; FaultPlan clock skew forcing timeouts
    sends the fast burn over the overload threshold with the slow window
    confirming — engine_health goes overloaded with slo_burn named in the
    reasons — and the window aging out recovers it to ok."""
    cfg, params = tiny
    clk = FakeClock(10.0)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=17,
                    max_model_len=64, clock=clk, double_buffer=False)
    h = eng.health()
    assert h["state"] == "ok" and h["burn_rates"]["1m"] == 0.0
    ok = eng.add_request(np.arange(5, dtype=np.int32), max_new_tokens=3,
                         deadline_s=1000.0)
    clk.t = 11.0
    eng.run()
    assert eng._outputs[ok].finish_reason in ("stop", "length")
    h = eng.health()
    assert h["state"] == "ok"
    assert h["burn_rates"]["1m"] == 0.0         # met on time: nothing burns
    assert eng.stats()["health"]["state"] == "ok"
    # injected clock skew: deadline evaluation sees now + 10000 s, so the
    # request times out on its first step — a 100% in-window miss rate over
    # the 1% error budget = burn 100 on both windows
    eng2 = LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=17,
                     max_model_len=64, clock=clk, double_buffer=False,
                     fault_plan=FaultPlan(skew_s=10_000.0))
    late = eng2.add_request(np.arange(5, dtype=np.int32), max_new_tokens=3,
                            deadline_s=5.0)
    clk.t += 1.0
    eng2.step()
    assert eng2._outputs[late].finish_reason == "timeout"
    clk.t += 1.0
    eng2.step()                                 # sample the rings post-miss
    h = eng2.health()
    assert h["burn_rates"]["1m"] == pytest.approx(100.0)
    assert h["burn_rates"]["5m"] == pytest.approx(100.0)
    assert h["state"] == "overloaded"
    assert h["signals"]["slo_burn"]["state"] == "overloaded"
    assert any(r.startswith("slo_burn") for r in h["reasons"])
    assert eng2._health_code() == 2.0
    # timeouts are also admission saturation: the signal fires on its own
    assert h["signals"]["admission"]["state"] != "ok"
    # recovery: the miss ages past every window — burn and rates decay to
    # exactly 0 and health folds back to ok without any reset
    clk.t += 400.0
    h = eng2.health()
    assert h["burn_rates"] == {"10s": 0.0, "1m": 0.0, "5m": 0.0}
    assert h["state"] == "ok" and h["reasons"] == []


def test_healthz_503_roundtrip_forced_pressure(tiny):
    """Acceptance bar: over a real socket, FaultPlan-forced pool pressure
    drives /healthz to 503 with a structured reason, the fleet rollup is
    worst-of, and the window aging out (fake clock) recovers it to 200 —
    deterministically."""
    from paddle_tpu.inference.obs_server import ObservabilityServer
    cfg, params = tiny
    clk = FakeClock(0.0)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    prefill_chunk=8, admission="optimistic",
                    preempt="recompute", clock=clk, double_buffer=False,
                    fault_plan=FaultPlan(pressure_steps=(2, 3, 4, 5, 6, 7)))
    eng.add_request(np.arange(4, dtype=np.int32), max_new_tokens=20,
                    priority=0)
    eng.add_request(np.arange(4, 6, dtype=np.int32), max_new_tokens=20,
                    priority=1)
    steps = 0
    while eng.has_work and eng.stats()["preemptions"] < 3 and steps < 100:
        clk.t += 0.1
        eng.step()
        steps += 1
    st = eng.stats()
    assert st["preemptions"] >= 3
    # >= 3 preemptions inside ~a second of engine time: far over the 1/s
    # overload threshold on the 10s window
    assert st["rates"]["preemptions_per_sec"]["10s"] >= 1.0
    healthy = LLMEngine(params, cfg, num_slots=1, page_size=8,
                        max_model_len=64, clock=clk)
    fleet = FleetMetrics().add("sick", eng).add("fine", healthy)
    with ObservabilityServer(eng) as srv, \
            ObservabilityServer(fleet=fleet) as fsrv:
        code, text = _http_get(srv.url + "/healthz")
        body = json.loads(text)
        assert code == 503
        assert body["state"] == "overloaded"
        assert body["signals"]["preemption"]["state"] == "overloaded"
        assert any(r.startswith("preemption") for r in body["reasons"])
        # fleet mode: worst-of rollup + per-engine detail
        code, text = _http_get(fsrv.url + "/healthz")
        fb = json.loads(text)
        assert code == 503 and fb["state"] == "overloaded"
        assert fb["engines"]["sick"]["state"] == "overloaded"
        assert fb["engines"]["fine"]["state"] == "ok"
        # recovery: the preemption burst ages past the window — 200/ok
        # again with zero resets, on both surfaces
        clk.t += 400.0
        code, text = _http_get(srv.url + "/healthz")
        assert code == 200 and json.loads(text)["state"] == "ok"
        code, text = _http_get(fsrv.url + "/healthz")
        fb = json.loads(text)
        assert code == 200 and fb["state"] == "ok"
        # a wedged engine (health evaluation raises) is 503, never 200 —
        # the bug the hardcoded {"ok": true} stub had
        eng._rw_preemptions = None              # wreck it
        code, text = _http_get(srv.url + "/healthz")
        body = json.loads(text)
        assert code == 503 and body["state"] == "error"
        assert "health evaluation failed" in body["reasons"][0]
        # error payloads keep the report shape probes read (code/signals)
        assert body["code"] == 3 and body["signals"] == {}
        # the postmortem surfaces survive the wrecked signal plane too:
        # stats() degrades to an error health entry instead of raising,
        # so the debug bundle (which embeds it) still assembles
        st_err = eng.stats()
        assert st_err["health"]["state"] == "error"
        assert "health evaluation failed" in st_err["health"]["reasons"][0]
        assert "requests" in eng.debug_bundle()
    # drain what's left so the fixture engines don't leak state
    eng._rw_preemptions = eng.metrics._rate_windows["preemptions_per_sec"]
    while eng.has_work:
        clk.t += 0.1
        eng.step()


def test_health_gauge_fleet_merge_worst_of():
    """The engine_health gauge declares agg='max': a fleet with a degraded
    (1) and an overloaded (2) member reads 2 — worst-of, not the
    nonsensical sum 3."""
    from paddle_tpu.inference.metrics import FleetMetrics, MetricsRegistry
    a, b = MetricsRegistry(namespace="llm_engine"), \
        MetricsRegistry(namespace="llm_engine")
    a.gauge("engine_health", agg="max").set(1.0)
    b.gauge("engine_health", agg="max").set(2.0)
    fleet = FleetMetrics().add("e0", a).add("e1", b)
    assert fleet.merged().get("engine_health").value == 2.0
    snap = fleet.snapshot()
    assert snap["fleet"]["gauges"]["engine_health"] == 2.0
    assert snap["engines"]["e0"]["gauges"]["engine_health"] == 1.0


def test_roofline_drift_and_recompile_anomaly(tiny, monkeypatch):
    """The live roofline: warm_decode arms predicted_step_ms once (cached,
    zero dispatches), busy steps feed the measured EWMA and the drift
    gauge; the alert counter counts band-excursion TRANSITIONS; the
    steady-state recompile counter moves exactly on executable-count
    growth after warm and degrades health; reset_counters re-seeds it all."""
    from paddle_tpu.analysis.registry import SERVE_SLO
    cfg, params = tiny
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64)
    assert eng.stats()["roofline"]["predicted_step_ms"] is None
    assert eng.metrics.snapshot()["gauges"]["roofline_drift"] == 0.0
    eng.warm_decode()                       # arms the prediction
    p = eng.stats()["roofline"]["predicted_step_ms"]
    assert p is not None and p > 0
    assert eng.predicted_step_ms == p       # cached: one trace ever
    eng.add_request(np.arange(6, dtype=np.int32), max_new_tokens=4)
    eng.run()
    st = eng.stats()["roofline"]
    assert st["measured_step_ms"] > 0       # real clock: busy steps fed it
    assert st["drift"] == pytest.approx(st["measured_step_ms"] / p)
    assert eng.metrics.snapshot()["gauges"]["roofline_drift"] == \
        pytest.approx(st["drift"])
    assert st["steady_state_recompiles"] == 0   # fixed shapes: never
    # drift-band alerts count transitions, not steps spent out of band
    # (establish a known in-band state first: on a CPU host the real run's
    # drift may already sit outside the declared band)
    monkeypatch.setitem(SERVE_SLO, "roofline_drift_band", (1e-9, 1e9))
    eng._note_steady_state(0.001)
    assert eng._drift_violation is False
    alerts0 = eng._roofline_alerts.value
    monkeypatch.setitem(SERVE_SLO, "roofline_drift_band", (1e-9, 1e-8))
    eng._note_steady_state(0.001)           # excursion begins: +1
    eng._note_steady_state(0.001)           # still out: no double count
    assert eng._roofline_alerts.value == alerts0 + 1
    monkeypatch.setitem(SERVE_SLO, "roofline_drift_band", (1e-9, 1e9))
    eng._note_steady_state(0.001)           # back in band
    monkeypatch.setitem(SERVE_SLO, "roofline_drift_band", (1e-9, 1e-8))
    eng._note_steady_state(0.001)           # second excursion: +1
    assert eng._roofline_alerts.value == alerts0 + 2
    # steady-state recompile anomaly: decode-side cache growth after the
    # baseline step is counted and degrades health
    class _Growing:
        n = 1

        def _cache_size(self):
            return self.n

    fake = _Growing()
    monkeypatch.setattr(eng, "_decode_fn", fake)
    eng._exec_baseline = None
    eng._note_steady_state(0.001)           # baseline fixed at 1
    assert eng._ss_recompiles.value == 0
    fake.n = 3
    eng._note_steady_state(0.001)           # grew after warm: anomaly
    assert eng._ss_recompiles.value == 2
    h = eng.health()
    assert h["signals"]["recompiles"]["state"] == "degraded"
    assert h["state"] != "ok"
    assert any(r.startswith("recompiles") for r in h["reasons"])
    # the reset contract: counters, EWMA and the baseline re-seed; the
    # static prediction survives (a property of shapes, not of a run)
    eng.reset_counters()
    st = eng.stats()["roofline"]
    assert st["steady_state_recompiles"] == 0 and st["drift_alerts"] == 0
    assert st["measured_step_ms"] is None and st["drift"] is None
    assert st["predicted_step_ms"] == p


def test_check_bench_tool(tiny, tmp_path):
    """Satellite (CI wiring): the trajectory row projects from a real
    run_serve_bench result and validates; SERVE_PERF_FLOORS (declared once
    in the analysis registry) pass the real row and catch tampered parity /
    dispatch / overhead / roofline values; append + read round-trips and
    malformed history lines are named."""
    import tools.check_bench as cb
    from bench_serve import run_serve_bench
    cfg, params = tiny
    result = run_serve_bench(config=cfg, params=params, num_requests=6,
                             num_slots=2, page_size=8, max_model_len=64,
                             max_new_tokens=4, prefill_chunk=8, spec_len=2,
                             debug_bundle_dir="")
    row = cb.bench_row(result)
    assert cb.validate_row(row) == []
    assert cb.check_floors(row) == []           # the real row passes
    assert row["mode"]["fused"] is True and row["mode"]["mp"] == 1
    assert row["perf"]["dispatches_per_step"] <= 1.0
    assert row["perf"]["model_error"] > 0
    # floors catch each declared regression class
    bad = json.loads(json.dumps(row))
    bad["parity"]["fuse_parity"] = False
    assert any("fuse_parity" in e for e in cb.check_floors(bad))
    bad = json.loads(json.dumps(row))
    bad["perf"]["dispatches_per_step"] = 2.0
    assert any("dispatches_per_step" in e for e in cb.check_floors(bad))
    bad = json.loads(json.dumps(row))
    bad["perf"]["tracing_overhead_measured"] = 0.5
    bad["perf"]["tracing_overhead"] = 0.5
    assert any("tracing overhead" in e for e in cb.check_floors(bad))
    bad["perf"]["tracing_overhead"] = None      # raw-run shape: only the
    assert any("tracing overhead" in e         # measured account exists —
               for e in cb.check_floors(bad))  # the bar must still bind
    bad = json.loads(json.dumps(row))
    bad["perf"]["model_error"] = None
    assert any("model_error" in e for e in cb.check_floors(bad))
    # schema-versioned append + read round-trip
    hist = tmp_path / "BENCH_SERVE.jsonl"
    cb.append_bench_row(result, path=str(hist))
    cb.append_bench_row(result, path=str(hist))
    rows, errors = cb.read_history(str(hist))
    assert len(rows) == 2 and errors == []
    assert rows[0][1]["schema_version"] == cb.ROW_SCHEMA_VERSION
    with open(hist, "a") as f:
        f.write("not json\n")
    _, errors = cb.read_history(str(hist))
    assert errors and "not JSON" in errors[0]
    # a bench that cannot produce a valid row fails loudly
    with pytest.raises(ValueError, match="trajectory row"):
        cb.append_bench_row({"garbage": True}, path=str(hist))
    # CLI: default mode schema-checks the history file
    assert cb.main(["--history", str(hist)]) == 1       # the bad line
    # a red run must not mutate the trajectory: the history pass runs
    # BEFORE any append, so a rerun cannot stack duplicate rows
    res_json = tmp_path / "res.json"
    res_json.write_text(json.dumps(result))
    size_before = hist.stat().st_size
    assert cb.main(["--history", str(hist),
                    "--from-json", str(res_json)]) == 1
    assert hist.stat().st_size == size_before
    hist2 = tmp_path / "clean.jsonl"
    cb.append_bench_row(result, path=str(hist2))
    assert cb.main(["--history", str(hist2)]) == 0
    # a green --from-json run IS a trajectory point
    assert cb.main(["--history", str(hist2),
                    "--from-json", str(res_json)]) == 0
    assert len(cb.read_history(str(hist2))[0]) == 2


def test_check_metrics_tool(tmp_path):
    """Satellite (CI wiring): the metrics schema guard passes on the live
    engine and its parser rejects malformed exposition text."""
    import tools.check_metrics as cm
    errors = []
    eng, st = cm.run_smoke(errors)
    assert not errors, errors
    assert cm.REQUIRED_STATS_KEYS <= set(st)
    check_errors = []
    cm.check_exposition(eng.metrics.to_prometheus(), check_errors)
    assert not check_errors, check_errors
    with pytest.raises(ValueError, match="malformed sample"):
        cm.parse_prometheus("bad metric line {")
    broken = ('m_bucket{le="1"} 5\nm_bucket{le="+Inf"} 3\n'
              'm_sum 1.0\nm_count 3\n')
    errs = []
    cm.check_exposition(broken, errs)
    assert any("cumulative" in e for e in errs)
