"""tpu_cost static resource accounting: golden hand-computed byte/flop
counts on toy programs, mp sharded-vs-replicated at-rest math, donation-
aware liveness, collective accounting cross-checked against the jaxpr,
JXP006/JXP007/JXP008 budget enforcement, CLI exit codes, and the bench's
roofline fields (ref: the reference's memory-optimize / inference-analysis
passes over the graph)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis.cost_model import (
    AtRestAccount, BufferAccount, audit_resources, collective_costs,
    device_spec, engine_at_rest, engine_step_cost, program_cost,
    run_cost_checks)
from paddle_tpu.analysis.jaxpr_checks import _build_engine, serving_targets
from paddle_tpu.analysis.registry import SERVE_RESOURCE_BUDGET

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# golden counts on toy programs (every number hand-computed)
# ---------------------------------------------------------------------------

def test_matmul_golden_flops_and_bytes():
    """[4,8] @ [8,16] f32: flops = 2*M*K*N = 1024; args = 128 + 512 bytes;
    out = 256; the product is the only defined value, live to the end, so
    the watermark is exactly the output and peak = args + out."""
    fn = jax.jit(lambda a, b: a @ b)
    c = program_cost("mm", fn, (jnp.ones((4, 8), jnp.float32),
                                jnp.ones((8, 16), jnp.float32)))
    assert c.flops == 2 * 4 * 8 * 16 == 1024
    assert c.arg_bytes == 4 * 8 * 4 + 8 * 16 * 4 == 640
    assert c.out_bytes == 4 * 16 * 4 == 256
    assert c.temp_peak_bytes == 256
    assert c.peak_bytes == 896
    assert "dot_general" in c.peak_at
    assert c.hbm_min_bytes == 896
    assert c.collectives is None        # not compiled


def test_elementwise_chain_liveness_peak():
    """((a*2)+1)*3 over [1024] f32: three elementwise eqns, 4096 B each.
    The watermark is two simultaneously-live temporaries (t1 while t2 is
    produced) = 8192 B — NOT the 12288 B sum of all three, because t1 dies
    at its last use."""
    fn = jax.jit(lambda a: ((a * 2) + 1) * 3)
    c = program_cost("chain", fn, (jnp.ones((1024,), jnp.float32),))
    assert c.flops == 3 * 1024
    assert c.arg_bytes == 4096 and c.out_bytes == 4096
    assert c.temp_peak_bytes == 8192
    assert c.peak_bytes == 4096 + 8192


def test_donation_excluded_from_peak():
    """The donated pool aliases its output: the output allocates nothing, so
    donating removes exactly pool-bytes from the modeled peak."""
    pool = jnp.zeros((16384,), jnp.float32)     # 65536 B
    x = jnp.ones((), jnp.float32)

    def body(pool, x):
        return pool.at[0].set(x), x + 1

    donated = program_cost("d", jax.jit(body, donate_argnums=(0,)), (pool, x))
    plain = program_cost("p", jax.jit(body), (pool, x))
    assert donated.alias_bytes == 65536
    assert plain.alias_bytes == 0
    assert plain.peak_bytes - donated.peak_bytes == 65536
    # donation also removes the output copy from the compulsory-traffic floor
    assert plain.hbm_min_bytes - donated.hbm_min_bytes == 65536


def test_cond_takes_max_branch_not_sum():
    """`lax.cond` executes one branch: flops are the worst branch, not the
    sum of both."""
    w = jnp.ones((32, 32), jnp.float32)

    def heavy(x):
        return x @ w                        # 2*32*32 flops

    def light(x):
        return x * 2.0                      # 32 flops

    fn = jax.jit(lambda p, x: jax.lax.cond(p, heavy, light, x))
    c = program_cost("cond", fn, (jnp.array(True), jnp.ones((32,),
                                                            jnp.float32)))
    # the heavy branch + the predicate's 1-element convert — NOT both
    # branches (2048 + 32 would mean the light branch was summed in)
    assert 2 * 32 * 32 <= c.flops < 2 * 32 * 32 + 32


def test_scan_multiplies_body_flops():
    """A scanned body's flops count once per trip: 8 iterations of a
    [16]x[16,16] matvec = 8 * 2*16*16 flops."""
    w = jnp.ones((16, 16), jnp.float32)

    def step(x, _):
        return x @ w, None

    fn = jax.jit(lambda x: jax.lax.scan(step, x, None, length=8)[0])
    c = program_cost("scan", fn, (jnp.ones((16,), jnp.float32),))
    assert c.flops == 8 * 2 * 16 * 16


# ---------------------------------------------------------------------------
# at-rest HBM: sharded vs replicated under mp
# ---------------------------------------------------------------------------

def test_at_rest_mp2_halves_sharded_keeps_replicated():
    """The mp=2 engine holds half the sharded param bytes and half the page
    pool per device, with the replicated set (norms, row biases — the
    embedding/head now lives in the SHARDED column) byte-identical to mp=1 —
    the memory math behind 'per-chip block memory drops by mp x' and the
    JXP006 ceiling's denominator."""
    e1, _ = _build_engine(1)
    e2, _ = _build_engine(2)
    a1, a2 = engine_at_rest(e1), engine_at_rest(e2)
    assert a1.mp == 1 and a2.mp == 2
    assert a1.param_bytes_sharded == a2.param_bytes_sharded        # global
    assert a2.param_bytes_sharded_per_device * 2 == \
        a1.param_bytes_sharded_per_device
    assert a1.param_bytes_replicated == a2.param_bytes_replicated
    assert a2.pool_bytes_per_device * 2 == a1.pool_bytes_per_device
    # the tied embedding/head is vocab-sharded (its per-device share halves
    # with mp); what remains replicated is the small norm/bias tail, all of
    # it under the declared JXP006 ceiling
    wte = next(b for b in a2.buffers if b.name == "wte")
    assert wte.sharded
    assert wte.bytes == e1.config.vocab_size * e1.config.hidden_size * 4
    assert a2.param_bytes_replicated < wte.bytes


def test_jxp006_replicated_ceiling():
    """A replicated buffer above the ceiling is flagged at mp>1 and named —
    but never the vocab-sharded `wte`, which left the replicated column; on
    one chip replication is free and the ceiling does not apply."""
    e2, _ = _build_engine(2)
    a2 = engine_at_rest(e2)
    # squeeze below the largest surviving replicated leaf: JXP006 fires and
    # names a norm/bias buffer, not the (sharded) embedding/head
    top = max((b for b in a2.buffers
               if not b.sharded and not b.name.startswith("pool.")),
              key=lambda b: b.bytes)
    _, fs = audit_resources([], a2,
                            {"replicated_bytes_ceiling": top.bytes - 1})
    assert any(f.rule == "JXP006" and f"`{top.name}`" in f.message
               for f in fs)
    assert not any("wte" in f.message for f in fs)
    _, fs = audit_resources([], a2, {"replicated_bytes_ceiling": 1 << 30})
    assert fs == []
    e1, _ = _build_engine(1)
    _, fs = audit_resources([], engine_at_rest(e1),
                            {"replicated_bytes_ceiling": 1000})
    assert fs == []


# ---------------------------------------------------------------------------
# collective accounting
# ---------------------------------------------------------------------------

def _toy_psum_target():
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.parallel.ring_attention import shard_map_compat

    mesh = Mesh(np.array(jax.devices()[:2]), ("mp",))
    fn = jax.jit(shard_map_compat(lambda x: jax.lax.psum(x, "mp"),
                                  mesh=mesh, axis_names=("mp",),
                                  in_specs=(P("mp"),), out_specs=P()))
    return fn, (jnp.ones((8, 16), jnp.float32),)


def test_collective_total_matches_jaxpr():
    """The HLO-derived collective total equals the jaxpr's own psum payload:
    in_specs=P('mp') shards [8,16] to a per-device [4,16] f32 operand =
    256 bytes, one all-reduce, no loop multiplier."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    fn, args = _toy_psum_target()
    c = program_cost("toy.mp2.x", fn, args, compile_collectives=True)
    # ground truth straight from the traced program
    from jax.core import ClosedJaxpr, Jaxpr

    def psums(j):
        out = []
        for e in j.eqns:
            if e.primitive.name == "psum":
                out.append(e)
            for v in e.params.values():
                stack = [v]
                while stack:
                    s = stack.pop()
                    if isinstance(s, ClosedJaxpr):
                        out.extend(psums(s.jaxpr))
                    elif isinstance(s, Jaxpr):
                        out.extend(psums(s))
                    elif isinstance(s, (list, tuple)):
                        stack.extend(s)
        return out

    eqns = psums(jax.make_jaxpr(fn)(*args).jaxpr)
    assert len(eqns) == 1
    aval = eqns[0].invars[0].aval
    expect = int(np.prod(aval.shape)) * 4
    assert expect == 256
    assert c.collective_bytes == expect
    assert [o.kind for o in c.collectives] == ["all-reduce"]


def test_collective_loop_multiplier_parses_while_trips():
    """Collectives inside a while body multiply by the parsed trip count —
    the layer scan is where the serving programs' all-reduces live."""
    hlo = """\
HloModule toy

%cond (p: (s32[])) -> pred[] {
  %zero = s32[] constant(0)
  %c = s32[] constant(24)
  %p = (s32[]) parameter(0)
  %iv = s32[] get-tuple-element((s32[]) %p), index=0
  ROOT %lt = pred[] compare(s32[] %iv, s32[] %c), direction=LT
}

%body (p: (s32[])) -> (s32[]) {
  %p = (s32[]) parameter(0)
  %ar = f32[2,64]{1,0} all-reduce(f32[2,64]{1,0} %x), to_apply=%add
  ROOT %t = (s32[]) tuple(%iv)
}

ENTRY %main () -> s32[] {
  %w = (s32[]) while((s32[]) %init), condition=%cond, body=%body
  %top = bf16[8]{0} all-gather(bf16[8]{0} %y), dimensions={0}
  %ars = (f32[16]{0}, f32[16]{0}) all-reduce-start(f32[16]{0} %z), to_apply=%add
  %ard = f32[16]{0} all-reduce-done((f32[16]{0}, f32[16]{0}) %ars)
  ROOT %r = s32[] get-tuple-element((s32[]) %w), index=0
}
"""
    ops = collective_costs(hlo)
    by_kind = {o.kind: [x for x in ops if x.kind == o.kind] for o in ops}
    # trip count resolved from the LT compare's constant OPERAND — the
    # folded constant(0) above it must not become a zero multiplier
    (ar_loop,) = [o for o in by_kind["all-reduce"] if o.multiplier > 1]
    assert ar_loop.multiplier == 24
    assert ar_loop.payload_bytes == 2 * 64 * 4
    assert ar_loop.bytes_per_step == 24 * 512
    (ag,) = by_kind["all-gather"]
    assert ag.multiplier == 1 and ag.payload_bytes == 8 * 2
    # async TPU form: the -start instruction counts ONCE at its largest
    # tuple component; the paired -done is not a second transfer
    starts = [o for o in by_kind["all-reduce"] if o.multiplier == 1]
    assert len(starts) == 1 and starts[0].payload_bytes == 16 * 4


def test_jxp007_undeclared_and_oversized_collective():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    fn, args = _toy_psum_target()
    e1, _ = _build_engine(1)
    at_rest = engine_at_rest(e1)
    target = [("toy.mp2.x", fn, args, {})]
    # undeclared: any collective traffic without a registry entry fails
    _, fs = audit_resources(target, at_rest, {})
    assert any(f.rule == "JXP007" and "undeclared" in f.message for f in fs)
    # declared but over budget fails with the measured total in the message
    _, fs = audit_resources(
        target, at_rest, {"collective_bytes_per_step": {"toy.mp2.x": 100}})
    assert any(f.rule == "JXP007" and "exceeds" in f.message for f in fs)
    # declared with headroom passes
    _, fs = audit_resources(
        target, at_rest, {"collective_bytes_per_step": {"toy.mp2.x": 1024}})
    assert [f for f in fs if f.rule == "JXP007"] == []


def test_jxp008_peak_budget_enforced():
    fn = jax.jit(lambda a, b: a @ b)
    args = (jnp.ones((4, 8), jnp.float32), jnp.ones((8, 16), jnp.float32))
    e1, _ = _build_engine(1)
    at_rest = engine_at_rest(e1)
    _, fs = audit_resources([("toy.mm", fn, args, {})], at_rest,
                            {"peak_hbm_bytes": {"mm": 10}},
                            compile_collectives=False)
    assert any(f.rule == "JXP008" for f in fs)
    _, fs = audit_resources([("toy.mm", fn, args, {})], at_rest,
                            {"peak_hbm_bytes": {"mm": 1 << 20}},
                            compile_collectives=False)
    assert fs == []


# ---------------------------------------------------------------------------
# the real serving set against the declared budget
# ---------------------------------------------------------------------------

def test_serving_resource_budget_clean():
    """The registry-declared SERVE_RESOURCE_BUDGET holds over the live
    serving executables at mp1 (and mp2 when the host has the chips):
    no oversized replicated buffer, no undeclared/oversized collective, no
    peak over budget — the CI gate `tools/tpu_cost.py --ci` enforces."""
    reports, findings = run_cost_checks(include_mp=True)
    assert findings == [], [f.format() for f in findings]
    rep1 = reports[1]
    # mp1 programs must be collective-free (single chip, nothing to talk to)
    for p in rep1["programs"]:
        assert p.get("collective_bytes_per_step", 0) == 0, p["name"]
    # the fused step's host-visible output stays O(B*K) ints: everything
    # except the donated pool alias is tiny
    fused = next(p for p in rep1["programs"] if "fused" in p["name"])
    assert fused["out_bytes"] - fused["alias_bytes"] < 1024
    if 2 in reports:
        # every declared communicating program exists in the mp pass whose
        # namespace it carries (serve.mp2.* under mp=2, serve.mp4.* under
        # mp=4) — a stale registry key fails here, an undeclared collective
        # fails JXP007 above
        names = {p["name"] for m, rep in reports.items() if m > 1
                 for p in rep["programs"]}
        declared = {k for k in SERVE_RESOURCE_BUDGET[
            "collective_bytes_per_step"]
            if int(k.split(".")[1][2:]) in reports}
        assert declared <= names


def test_engine_step_cost_traces_without_dispatch():
    """The bench hook costs the engine's own decode-side program abstractly:
    no compile, no dispatch at mp1 — program-count stats untouched."""
    eng, _ = _build_engine(1)
    before = eng.stats()["decode_executables"]
    c = engine_step_cost(eng)
    assert eng.stats()["decode_executables"] == before
    assert c.flops > 0 and c.peak_bytes > c.arg_bytes
    assert c.alias_bytes > 0            # the donated pool aliases out
    assert c.collectives is None        # single chip: compile skipped
    ms = c.predicted_ms(device_spec())
    assert 0 < ms < 1e3


def test_engine_step_cost_mp2_carries_collectives():
    """At mp>1 the bench hook compiles (with the engine's real shardings)
    so its roofline carries the same ICI term tpu_cost reports — the bench
    JSON and the CLI cannot disagree about the fused step.  The compile
    goes through lower(), outside the AOT dispatch cache, so the measured
    program counts stay exact."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    eng, _ = _build_engine(2)
    before = eng.stats()["decode_executables"]
    c = engine_step_cost(eng)
    assert eng.stats()["decode_executables"] == before
    assert c.collectives is not None and c.collective_bytes > 0
    # the ICI term must actually move the prediction
    spec = device_spec()
    no_coll = dataclasses_replace_collectives(c)
    assert c.predicted_ms(spec, mp=2) > no_coll.predicted_ms(spec, mp=2)


def dataclasses_replace_collectives(c):
    import dataclasses
    return dataclasses.replace(c, collectives=[])


# ---------------------------------------------------------------------------
# bench integration + CLI exit codes
# ---------------------------------------------------------------------------

def test_bench_reports_roofline_fields():
    """bench_serve emits predicted_step_ms next to the measured step time;
    on the CPU smoke the model is sanity-bounded, not tight."""
    from bench_serve import run_serve_bench
    st = run_serve_bench(num_requests=6, num_slots=2, page_size=8,
                         max_model_len=64, max_new_tokens=4,
                         prefill_chunk="auto", spec_len=2, seed=5)
    assert st["predicted_step_ms"] > 0
    assert st["measured_step_ms"] > 0
    assert st["model_error"] is not None and st["model_error"] > 0
    assert np.isfinite(st["model_error"])
    assert st["device_spec"]
    # "auto" resolved by the engine to the spec lane's width
    assert st["prefill_chunk"] == 3


def test_auto_prefill_chunk_resolution_and_parity():
    """prefill_chunk='auto' picks spec_len+1 (one page when spec is off), so
    the fused program's width never exceeds what verify already needs — and
    greedy tokens are byte-identical to an explicit chunk and to bucketed
    mode."""
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models import gpt as gpt_mod

    cfg = gpt_mod.gpt_tiny(64)
    params = gpt_mod.init_params(cfg, jax.random.key(0))
    kw = dict(num_slots=2, page_size=8, max_model_len=64)
    auto = LLMEngine(params, cfg, prefill_chunk="auto", spec_len=4, **kw)
    assert auto.prefill_chunk == 5 and auto._fused_T == 5
    off = LLMEngine(params, cfg, prefill_chunk="auto", spec_len=0, **kw)
    assert off.prefill_chunk == 8       # one page

    def run(eng):
        rng = np.random.RandomState(7)
        for i in range(4):
            eng.add_request(rng.randint(0, cfg.vocab_size, (9 + 4 * i,))
                            .astype(np.int32), max_new_tokens=5)
        return {k: list(v.token_ids) for k, v in eng.run().items()}

    a = run(LLMEngine(params, cfg, prefill_chunk="auto", spec_len=2, **kw))
    b = run(LLMEngine(params, cfg, prefill_chunk=3, spec_len=2, **kw))
    c = run(LLMEngine(params, cfg, spec_len=2, **kw))
    assert a == b == c


def test_cli_ci_exit_codes(tmp_path):
    """--ci exits 0 against the declared budget and nonzero when an injected
    budget makes every program oversized (the subprocess proof that a budget
    regression cannot slide through CI)."""
    tool = os.path.join(REPO, "tools", "tpu_cost.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, tool, "--ci", "--no-mp", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=420)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    payload = json.loads(ok.stdout)
    assert payload["ok"] and payload["reports"]["mp1"]["programs"]
    bad = subprocess.run(
        [sys.executable, tool, "--ci", "--no-mp", "--peak-budget", "1"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=420)
    assert bad.returncode == 1
    assert "JXP008" in bad.stdout
