"""Oversubscribed serving: optimistic admission, preemption with KV
swap/recompute, deadlines, fail-fast rejection, and the fault-injection
harness that drives every preempt interleaving deterministically (ref vLLM
preempt-then-swap-or-recompute, Kwon et al. SOSP 2023 §4.3, over Sarathi
chunked prefill).

The two hard bars, asserted throughout: (1) byte-exact greedy parity
preempted-vs-undisturbed — preemption may cost throughput, never tokens;
(2) zero leaked pages across preempt/swap/abort/timeout interleavings —
`PagedKVCache.check_invariants` (free/LRU/in-use + the fourth `swapped`
partition) clean at every step boundary and empty at drain."""
import numpy as np
import pytest

import jax

from paddle_tpu.inference.cache import PagedKVCache
from paddle_tpu.inference.engine import LLMEngine
from paddle_tpu.inference.faults import FaultInjected, FaultPlan
from paddle_tpu.models import gpt as G


@pytest.fixture(scope="module")
def cfg():
    return G.gpt_tiny(64)


@pytest.fixture(scope="module")
def params(cfg):
    return G.init_params(cfg, jax.random.key(0))


def _prompts(cfg, n=6, lo=4, hi=9, seed=1):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, cfg.vocab_size,
                        (int(rng.randint(lo, hi)),)).astype(np.int32)
            for _ in range(n)]


@pytest.fixture(scope="module")
def reference(cfg, params):
    """Undisturbed run: big pool, reservation admission — the token oracle
    every preempted run must match byte-for-byte."""
    prompts = _prompts(cfg)
    eng = LLMEngine(params, cfg, num_slots=6, page_size=8, max_model_len=64,
                    prefill_chunk=8)
    rids = [eng.add_request(p, max_new_tokens=24) for p in prompts]
    outs = eng.run()
    return prompts, [list(outs[r].token_ids) for r in rids]


def _drain_checked(eng):
    """step() to completion, asserting page invariants at EVERY boundary."""
    while eng.has_work:
        eng.step()
        eng.cache.check_invariants()
    st = eng.stats()
    assert st["pages_in_use"] == 0 and st["swapped"] == 0
    return dict(eng._outputs), st


def _assert_parity(outs, rids, ref_tokens):
    for rid, ref in zip(rids, ref_tokens):
        assert outs[rid].finish_reason in ("stop", "length")
        assert list(outs[rid].token_ids) == ref, \
            f"request {rid} diverged under preemption"


# ---------------------------------------------------------------------------
# optimistic admission + token-granular growth
# ---------------------------------------------------------------------------

def test_optimistic_admission_beats_reservation_concurrency(cfg, params,
                                                            reference):
    """Reservation fits two 4-page worst-case footprints into an 8-page
    pool; optimistic admits on 1-page prompts and runs several slots off
    live tokens instead."""
    prompts, ref_tokens = reference

    def peak_running(admission):
        eng = LLMEngine(params, cfg, num_slots=6, page_size=8, num_pages=9,
                        max_model_len=64, prefill_chunk=8,
                        admission=admission)
        rids = [eng.add_request(p, max_new_tokens=24) for p in prompts]
        peak = 0
        while eng.has_work:
            eng.step()
            peak = max(peak, eng.stats()["running"])
            eng.cache.check_invariants()
        outs = dict(eng._outputs)
        _assert_parity(outs, rids, ref_tokens)
        return peak

    assert peak_running("reservation") <= 2
    assert peak_running("optimistic") >= 4


def test_optimistic_admits_watermark_sized_footprint_when_idle(cfg, params):
    """Regression: a prompt whose footprint sits within the admission
    watermark of the WHOLE pool passes intake (it fits), so an idle engine
    must admit it rather than wedge the queue head behind a watermark that
    protects nothing."""
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=5,
                    max_model_len=64, prefill_chunk=8,
                    admission="optimistic")
    # 30 + 2 = 32 tokens = all 4 real pages: feasible, zero slack
    rid = eng.add_request(np.arange(30, dtype=np.int32), max_new_tokens=2)
    outs = eng.run()
    assert outs[rid].finish_reason in ("stop", "length")
    eng.cache.check_invariants()


def test_optimistic_growth_tracks_live_tokens(cfg, params):
    """A lone decoding slot grows page by page — admission reserved only the
    prompt footprint, and the page count follows lengths upward."""
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    prefill_chunk=8, admission="optimistic")
    rid = eng.add_request(np.arange(6, dtype=np.int32), max_new_tokens=40)
    held = []
    while eng.has_work:
        eng.step()
        held.append(eng.cache.pages_held(0))
        eng.cache.check_invariants()
    assert held[0] == 1                     # prompt footprint only
    assert max(held) >= 5                   # grew with the 40-token decode
    assert eng._outputs[rid].finish_reason == "length"


# ---------------------------------------------------------------------------
# preemption: recompute and swap, byte parity + zero leaks
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("preempt", ["recompute", "swap"])
def test_preemption_parity_and_no_leaks(cfg, params, reference, preempt):
    prompts, ref_tokens = reference
    # kv_tier off: this test proves the PREEMPTION machinery's program
    # accounting in isolation (the default-on tier shares the two swap
    # executables and would mask a recompute path that wrongly compiled
    # them; the tier's own program accounting lives in tests/test_kv_tier)
    eng = LLMEngine(params, cfg, num_slots=6, page_size=8, num_pages=9,
                    max_model_len=64, prefill_chunk=8,
                    admission="optimistic", preempt=preempt, kv_tier=False)
    rids = [eng.add_request(p, max_new_tokens=24) for p in prompts]
    outs, st = _drain_checked(eng)
    assert st["preemptions"] > 0
    if preempt == "swap":
        assert st["preempt_swaps"] > 0 and st["swapped_pages"] > 0
        assert st["swap_executables"] == 2
        assert st["swap_ms"] >= 0.0
    else:
        assert st["preempt_recomputes"] == st["preemptions"]
        assert st["recomputed_tokens"] > 0
        assert st["swap_executables"] == 0
    _assert_parity(outs, rids, ref_tokens)
    for rid in rids:
        m = outs[rid].metrics
        assert m is not None and m.preemptions >= 0


def test_swap_pool_exhaustion_degrades_to_recompute(cfg, params, reference):
    """swap_pool_pages=0 leaves no host room: every preemption must fall
    back to recompute — same tokens, no swap executables ever built."""
    prompts, ref_tokens = reference
    eng = LLMEngine(params, cfg, num_slots=6, page_size=8, num_pages=9,
                    max_model_len=64, prefill_chunk=8,
                    admission="optimistic", preempt="swap",
                    swap_pool_pages=0)
    rids = [eng.add_request(p, max_new_tokens=24) for p in prompts]
    outs, st = _drain_checked(eng)
    assert st["preemptions"] > 0
    assert st["preempt_swaps"] == 0 and st["swapped_pages"] == 0
    assert st["preempt_recomputes"] == st["preemptions"]
    _assert_parity(outs, rids, ref_tokens)


def test_victim_selection_prefers_low_priority(cfg, params):
    """Under forced pressure the priority-0 request is evicted before the
    priority-1 request every time."""
    plan = FaultPlan(pressure_steps=(4,))
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    prefill_chunk=8, admission="optimistic",
                    fault_plan=plan)
    lo = eng.add_request(np.arange(4, dtype=np.int32), max_new_tokens=20,
                         priority=0)
    hi = eng.add_request(np.arange(4, 6, dtype=np.int32), max_new_tokens=20,
                         priority=1)
    preempted = set()
    while eng.has_work:
        eng.step()
        preempted |= set(eng._preempted)
        eng.cache.check_invariants()
    assert lo in preempted and hi not in preempted
    for rid in (lo, hi):
        assert eng._outputs[rid].finish_reason == "length"


@pytest.mark.parametrize("mode", ["bucketed", "unfused"])
def test_preemption_on_legacy_paths(cfg, params, reference, mode):
    """Growth + preemption also cover the bucketed one-shot prefill (a
    recompute resume replays its longer prompt through the bucket ladder)
    and the fuse=False three-program step (growth runs before the legacy
    verify/decode dispatches)."""
    prompts, ref_tokens = reference
    kw = dict(prefill_chunk=None) if mode == "bucketed" \
        else dict(prefill_chunk=8, fuse=False)
    eng = LLMEngine(params, cfg, num_slots=6, page_size=8, num_pages=9,
                    max_model_len=64, admission="optimistic", **kw)
    rids = [eng.add_request(p, max_new_tokens=24) for p in prompts]
    outs, st = _drain_checked(eng)
    assert st["preemptions"] > 0
    _assert_parity(outs, rids, ref_tokens)


# ---------------------------------------------------------------------------
# fault injection: forced pressure mid-verify / mid-chunk-prefill, failing
# swap copies — every path must keep parity and leak nothing
# ---------------------------------------------------------------------------

def test_forced_pressure_mid_verify_keeps_spec_parity(cfg, params):
    """Preemption in a step where victims carry speculative drafts: the
    in-flight draft is discarded with the victim, and the replay still
    reproduces the vanilla-greedy stream."""
    rng = np.random.RandomState(3)
    # repetitive prompts so the n-gram proposer actually drafts
    base = rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)
    prompts = [np.tile(base, 3)[:10 + i] for i in range(4)]

    ref_eng = LLMEngine(params, cfg, num_slots=4, page_size=8,
                        max_model_len=64, prefill_chunk=8)
    ref = [list(o.token_ids) for o in
           (lambda e, r: [e.run()[x] for x in r])(
               ref_eng, [ref_eng.add_request(p, max_new_tokens=20)
                         for p in prompts])]

    plan = FaultPlan(pressure_steps=(3, 5, 7))
    eng = LLMEngine(params, cfg, num_slots=4, page_size=8, max_model_len=64,
                    prefill_chunk=8, spec_len=3, admission="optimistic",
                    fault_plan=plan)
    rids = [eng.add_request(p, max_new_tokens=20) for p in prompts]
    outs, st = _drain_checked(eng)
    assert st["preemptions"] >= 1
    assert st["spec_events"] > 0, "verify lane never exercised"
    _assert_parity(outs, rids, ref)


def test_forced_pressure_mid_chunk_prefill(cfg, params):
    """Preemption while another slot is mid-chunk-prefill: the prefilling
    slot is untouched (its prompt pages are reserved), victims come from
    the decode set, and everyone finishes with exact tokens."""
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 6, 40, 7)]     # the 40-token prompt chunks 5x
    ref_eng = LLMEngine(params, cfg, num_slots=4, page_size=8,
                        max_model_len=64, prefill_chunk=8)
    rr = [ref_eng.add_request(p, max_new_tokens=16) for p in prompts]
    ref_outs = ref_eng.run()
    ref = [list(ref_outs[r].token_ids) for r in rr]

    plan = FaultPlan(pressure_steps=(2, 3, 4, 5, 6))
    eng = LLMEngine(params, cfg, num_slots=4, page_size=8, max_model_len=64,
                    prefill_chunk=8, admission="optimistic", fault_plan=plan)
    rids = [eng.add_request(p, max_new_tokens=16) for p in prompts]
    saw_prefilling_during_preempt = False
    while eng.has_work:
        pre = eng.stats()["preemptions"]
        eng.step()
        st = eng.stats()
        if st["preemptions"] > pre and st["prefilling"] > 0:
            saw_prefilling_during_preempt = True
        eng.cache.check_invariants()
    outs, st = dict(eng._outputs), eng.stats()
    assert st["preemptions"] >= 1
    assert saw_prefilling_during_preempt, \
        "no preemption landed while a chunk prefill was in progress"
    _assert_parity(outs, rids, ref)


@pytest.mark.parametrize("kw", [dict(fail_d2h=2), dict(fail_h2d=2)])
def test_swap_copy_failures_degrade_cleanly(cfg, params, reference, kw):
    """Injected d2h/h2d copy failures turn swaps into recomputes: the host
    obligation is cleared, pages balance, tokens unchanged."""
    prompts, ref_tokens = reference
    eng = LLMEngine(params, cfg, num_slots=6, page_size=8, num_pages=9,
                    max_model_len=64, prefill_chunk=8,
                    admission="optimistic", preempt="swap",
                    fault_plan=FaultPlan(**kw))
    rids = [eng.add_request(p, max_new_tokens=24) for p in prompts]
    outs, st = _drain_checked(eng)
    assert st["preemptions"] > 0
    assert st["preempt_recomputes"] > 0, "no swap ever degraded"
    if "fail_d2h" in kw:
        # a failed d2h never delivered KV to the host pool: it must count
        # as recompute ONLY, so the split sums exactly to preemptions
        assert st["preempt_swaps"] + st["preempt_recomputes"] == \
            st["preemptions"]
    else:
        # an h2d failure degrades a swap that HAD delivered (counted in
        # both swap and recompute) — the split may legitimately exceed
        assert st["preempt_swaps"] + st["preempt_recomputes"] >= \
            st["preemptions"]
    _assert_parity(outs, rids, ref_tokens)


def test_swap_then_abort_releases_host_pool(cfg, params, reference):
    prompts, _ = reference
    eng = LLMEngine(params, cfg, num_slots=6, page_size=8, num_pages=9,
                    max_model_len=64, prefill_chunk=8,
                    admission="optimistic", preempt="swap")
    rids = [eng.add_request(p, max_new_tokens=24) for p in prompts]
    aborted = None
    while eng.has_work:
        eng.step()
        eng.cache.check_invariants()
        if aborted is None:
            swapped = [r for r, rec in eng._preempted.items()
                       if rec["kind"] == "swap"]
            if swapped:
                assert eng.abort(swapped[0])
                aborted = swapped[0]
                eng.cache.check_invariants()
    assert aborted is not None, "no request was ever swapped out"
    out = eng._outputs[aborted]
    assert out.finish_reason == "abort"
    assert len(out.token_ids) > 0           # banked generation survives abort
    assert eng.cache.swapped_page_count == 0
    assert eng.stats()["pages_in_use"] == 0


def test_abort_during_recompute_replay_keeps_banked_tokens(cfg, params):
    """abort() of a preempted request mid-replay (back in the prefilling
    stage with `prior` tokens banked) publishes those tokens and the
    original TTFT — same contract as aborting it queued or running."""
    plan = FaultPlan(pressure_steps=(5,))
    # prefix_cache=False: with the cache on, the victim's own pages are
    # re-matched from the LRU and the replay completes inside one step —
    # a full multi-chunk replay is needed to catch the request mid-prefill
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    prefill_chunk=4, admission="optimistic",
                    prefix_cache=False, fault_plan=plan)
    rids = [eng.add_request(np.arange(8 + i, dtype=np.int32),
                            max_new_tokens=24) for i in range(2)]
    aborted = None
    while eng.has_work:
        eng.step()
        eng.cache.check_invariants()
        if aborted is None:
            resumed = [st for st in eng._prefilling.values() if st.prior]
            if resumed:
                st = resumed[0]
                banked = list(st.prior)
                assert eng.abort(st.request.request_id)
                aborted = st.request.request_id
                out = eng._outputs[aborted]
                assert out.finish_reason == "abort"
                assert list(out.token_ids) == banked
                assert out.ttft_s is not None
                eng.cache.check_invariants()
    assert aborted is not None, "no preempted request was caught mid-replay"
    assert eng.stats()["pages_in_use"] == 0


# ---------------------------------------------------------------------------
# deadlines + clock skew
# ---------------------------------------------------------------------------

def test_deadline_timeout_queued_and_running(cfg, params):
    t = [0.0]
    eng = LLMEngine(params, cfg, num_slots=1, page_size=8, num_pages=9,
                    max_model_len=64, prefill_chunk=8, clock=lambda: t[0])
    slow = eng.add_request(np.arange(4, dtype=np.int32), max_new_tokens=40,
                           deadline_s=5.0)
    queued = eng.add_request(np.arange(6, dtype=np.int32), max_new_tokens=4,
                             deadline_s=3.0)   # expires before its slot frees
    while eng.has_work:
        eng.step()
        t[0] += 1.0
        eng.cache.check_invariants()
    outs = eng._outputs
    assert outs[slow].finish_reason == "timeout"
    assert outs[queued].finish_reason == "timeout"
    assert outs[queued].metrics.t_first_token is None
    assert len(outs[slow].token_ids) > 0    # partial generation published
    st = eng.stats()
    assert st["timeouts"] == 2
    # timeouts are excluded from the e2e latency SLO like aborts
    assert st["latency"]["e2e_s"]["count"] == 0
    assert st["pages_in_use"] == 0


def test_deadline_during_swap(cfg, params):
    """A request whose deadline expires while its KV sits in the host swap
    pool: the obligation is dropped, reason is timeout, nothing leaks."""
    t = [0.0]
    plan = FaultPlan(pressure_steps=(4,))
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    prefill_chunk=8, admission="optimistic", preempt="swap",
                    fault_plan=plan, clock=lambda: t[0])
    rids = [eng.add_request(np.arange(4 + i, dtype=np.int32),
                            max_new_tokens=24, deadline_s=100.0)
            for i in range(2)]
    timed_out = None
    while eng.has_work:
        eng.step()
        t[0] += 1.0
        eng.cache.check_invariants()
        if timed_out is None and eng.stats()["swapped"] > 0:
            t[0] += 1000.0              # expire EVERYTHING, swapped included
            timed_out = True
    assert timed_out, "no request was swapped before the deadline jump"
    assert any(eng._outputs[r].finish_reason == "timeout" for r in rids)
    assert eng.cache.swapped_page_count == 0
    assert eng.stats()["pages_in_use"] == 0
    eng.cache.check_invariants()


def test_clock_skew_expires_early_but_cleanly(cfg, params):
    t = [0.0]
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                    prefill_chunk=8, fault_plan=FaultPlan(skew_s=1e6),
                    clock=lambda: t[0])
    rid = eng.add_request(np.arange(4, dtype=np.int32), max_new_tokens=20,
                          deadline_s=50.0)
    ok = eng.add_request(np.arange(5, dtype=np.int32), max_new_tokens=4)
    while eng.has_work:
        eng.step()
        t[0] += 0.01
        eng.cache.check_invariants()
    # the skewed clock expired the deadlined request at its first step; the
    # deadline-free request is untouched by skew
    assert eng._outputs[rid].finish_reason == "timeout"
    assert eng._outputs[ok].finish_reason in ("stop", "length")
    assert eng.stats()["pages_in_use"] == 0


# ---------------------------------------------------------------------------
# fail-fast rejection
# ---------------------------------------------------------------------------

def test_impossible_footprint_rejected_without_wedging(cfg, params):
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=3,
                    max_model_len=64)       # 2 real pages = 16 tokens
    big = eng.add_request(np.zeros((20,), np.int32), max_new_tokens=8)
    out = eng._outputs[big]
    assert out.finish_reason == "rejected" and out.token_ids == []
    assert eng.stats()["rejected_requests"] == 1
    assert eng.stats()["queued"] == 0       # never entered the queue
    # the queue head is NOT wedged: a feasible request behind it completes
    ok = eng.add_request(np.zeros((6,), np.int32), max_new_tokens=4)
    outs = eng.run()
    assert outs[ok].finish_reason in ("stop", "length")
    eng.cache.check_invariants()


def test_rejection_applies_under_optimistic_too(cfg, params):
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=3,
                    max_model_len=64, admission="optimistic")
    rid = eng.add_request(np.zeros((4,), np.int32), max_new_tokens=20)
    # prompt alone fits, but the worst-case footprint (24 tokens = 3 pages)
    # can never fit 2 real pages — optimistic growth would wedge at the end
    assert eng._outputs[rid].finish_reason == "rejected"


# ---------------------------------------------------------------------------
# cache-level unit coverage of the new machinery
# ---------------------------------------------------------------------------

def test_cache_grow_and_swap_partition_unit():
    mgr = PagedKVCache(num_pages=8, page_size=4, num_slots=2,
                       max_pages_per_slot=4)
    mgr.allocate(0, 4)                      # 1 page
    assert mgr.pages_held(0) == 1
    mgr.grow(0, 5)                          # crosses into page 2
    assert mgr.pages_held(0) == 2
    mgr.grow(0, 5)                          # idempotent
    assert mgr.pages_held(0) == 2
    mgr.check_invariants()
    with pytest.raises(ValueError, match="slot capacity"):
        mgr.grow(0, 17)
    mgr.note_swap_out(7, 2)
    assert mgr.swapped_page_count == 2 and mgr.swapped_requests == 1
    with pytest.raises(RuntimeError, match="already swapped"):
        mgr.note_swap_out(7, 1)
    mgr.check_invariants()
    assert mgr.note_swap_in(7) == 2
    assert mgr.swapped_page_count == 0
    mgr.release(0)
    mgr.check_invariants()
    # growth exhausts the pool -> RuntimeError (the preemption trigger):
    # slot 1 holds 4 of the 7 real pages, slot 0 one — growing slot 0 to
    # its 4-page capacity needs 3 fresh pages but only 2 remain
    mgr.allocate(1, 16)
    mgr.allocate(0, 4)
    with pytest.raises(RuntimeError, match="out of KV pages"):
        mgr.grow(0, 16)


def test_fault_plan_unit():
    plan = FaultPlan(pressure_steps=(2,), fail_d2h=1, skew_s=3.0)
    assert not plan.pool_pressure(1)
    assert plan.pool_pressure(2)
    assert not plan.pool_pressure(2)        # fires once per listed step
    with pytest.raises(FaultInjected):
        plan.d2h()
    plan.d2h()                              # budget spent: no-op
    plan.h2d()                              # never armed: no-op
    assert plan.skew() == 3.0


# ---------------------------------------------------------------------------
# the oversubscription bench smoke (the PR's acceptance bar)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("preempt", ["recompute", "swap"])
def test_bench_oversubscribe_completes_with_parity(preempt):
    from bench_serve import run_serve_bench
    kw = dict(num_requests=16, num_slots=4, page_size=8, max_model_len=64,
              max_new_tokens=12, prefill_chunk=8, seed=7, preempt=preempt)
    pressured = run_serve_bench(oversubscribe=2.0, **kw)
    base = run_serve_bench(oversubscribe=1.0, **kw)
    # every request completed (run_serve_bench asserts the count and the
    # drain invariants internally), pressure actually materialized, and the
    # stream is byte-identical to the unpressured run
    assert pressured["preemptions"] > 0
    assert pressured["outputs_digest"] == base["outputs_digest"]
    assert pressured["goodput_tokens_per_sec"] > 0
    if preempt == "swap":
        assert pressured["preempt_swaps"] > 0
        assert pressured["swap_executables"] == 2
