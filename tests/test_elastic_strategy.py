"""ElasticManager decisions + elastic launch scale-down + DistributedStrategy
-> MeshConfig lowering (ref fleet/elastic/manager.py:126,
fleet/base/distributed_strategy.py:121)."""
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                  ElasticStatus, parse_np)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_parse_np():
    assert parse_np("2:4") == (2, 4)
    assert parse_np("3") == (3, 3)
    assert parse_np(4) == (4, 4)
    with pytest.raises(ValueError):
        parse_np("4:2")


def test_manager_normal_and_reported_failure():
    clk = FakeClock()
    mgr = ElasticManager("2:4", timeout=10.0, clock=clk)
    for r in range(4):
        mgr.register(r)
    assert mgr.decide() == ElasticStatus.NORMAL
    mgr.report_failure(3)
    assert mgr.decide() == ElasticStatus.RESTART  # no grace for process exit
    assert mgr.scaled_np() == 3                   # scale down to live count


def test_manager_stale_heartbeat_grace_then_restart():
    clk = FakeClock()
    mgr = ElasticManager("1:2", timeout=10.0, clock=clk)
    mgr.register(0)
    mgr.register(1)
    clk.t = 11.0
    mgr.heartbeat(0)                              # rank 1 goes silent
    assert mgr.decide() == ElasticStatus.HOLD     # inside grace window
    clk.t = 22.0
    mgr.heartbeat(0)
    assert mgr.decide() == ElasticStatus.RESTART
    assert mgr.scaled_np() == 1


def test_manager_exit_when_below_min_and_exhausted():
    clk = FakeClock()
    mgr = ElasticManager("2:2", timeout=1.0, max_restart=1, clock=clk)
    mgr.register(0)
    mgr.register(1)
    mgr.report_failure(0)
    mgr.report_failure(1)
    assert mgr.decide() == ElasticStatus.RESTART  # retry budget left
    mgr.on_restart()
    mgr.register(0)
    mgr.register(1)
    mgr.report_failure(0)
    mgr.report_failure(1)
    assert mgr.decide() == ElasticStatus.EXIT


def test_elastic_launch_scales_down(tmp_path):
    """rank>=1 always dies -> elastic relaunch with np=1 -> success."""
    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, sys\n"
        "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "world = int(os.environ['PADDLE_TRAINERS_NUM'])\n"
        "if rank >= 1:\n"
        "    sys.exit(1)\n"
        "print(f'SURVIVOR world={world}', flush=True)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    log_dir = str(tmp_path / "logs")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--np", "1:2", "--elastic_level", "1",
         "--log_dir", log_dir, str(script)],
        env=env, capture_output=True, text=True, timeout=120,
        cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "elastic relaunch 1/" in proc.stdout and "np=1" in proc.stdout
    logs = "".join(open(os.path.join(log_dir, f), errors="replace").read()
                   for f in os.listdir(log_dir))
    assert "SURVIVOR world=1" in logs


def test_distributed_strategy_to_mesh_config():
    import paddle_tpu.distributed.fleet as fleet
    s = fleet.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                        "sharding_degree": 1, "sep_degree": 1,
                        "mp_configs": {"sequence_parallel": True},
                        "pp_configs": {}}
    s.recompute = True
    s.sharding = True
    s.sharding_configs = {"sharding_degree": 2, "stage": 2, "offload": False,
                          "accumulate_steps": 1}
    s.pipeline = True
    s.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 1,
                          "schedule_mode": "1F1B"}
    mc = s.to_mesh_config()
    assert (mc.dp, mc.pp, mc.sharding, mc.mp) == (2, 2, 2, 2)
    assert mc.sharding_stage == 2
    assert mc.micro_batches == 4
    assert mc.sequence_parallel and mc.remat
    assert mc.size == 16


def test_engine_accepts_strategy():
    import jax
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.models.gpt import gpt_tiny
    s = DistributedStrategy()
    s.hybrid_configs["dp_degree"] = 2
    s.hybrid_configs["mp_degree"] = 2
    eng = Engine(config=gpt_tiny(64), strategy=s, devices=jax.devices()[:4],
                 seed=0)
    assert eng.trainer.cfg.dp == 2 and eng.trainer.cfg.mp == 2
    rng = np.random.RandomState(0)
    tok = rng.randint(0, 256, (8, 64)).astype(np.int32)
    loss = float(eng.trainer.train_step(tok, np.roll(tok, -1, 1)))
    assert np.isfinite(loss)
